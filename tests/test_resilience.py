"""Unit tests for the fault-tolerance runtime (runtime/resilience.py):
retry policy + classification, watchdog deadlines, failure ledger, the
deterministic fault injector, and the CheckpointManager integration
(prefetch-error recovery, stale-pending regression)."""

import json
import os
import time

import pytest

from taboo_brittleness_tpu.runtime import resilience
from taboo_brittleness_tpu.runtime.resilience import (
    Deadline, DeadlineExceeded, FailureLedger, FaultInjector, FaultSpec,
    InjectedFault, InjectedPermanentFault, RetryPolicy, run_with_deadline)


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test gets a fresh process-wide injector (and leaves none)."""
    resilience.set_injector(FaultInjector())
    yield
    resilience.set_injector(FaultInjector())


# ---------------------------------------------------------------------------
# Classification.
# ---------------------------------------------------------------------------

def test_error_classification():
    assert resilience.is_transient(OSError("flaky read"))
    assert resilience.is_transient(TimeoutError("slow"))
    assert resilience.is_transient(ConnectionResetError("reset"))
    assert resilience.is_transient(DeadlineExceeded("over budget"))
    assert resilience.is_transient(InjectedFault("injected"))
    # Permanent: missing/forbidden files, logic errors, injected-permanent.
    assert not resilience.is_transient(FileNotFoundError("no shard"))
    assert not resilience.is_transient(PermissionError("denied"))
    assert not resilience.is_transient(ValueError("bad shape"))
    assert not resilience.is_transient(KeyError("missing"))
    assert not resilience.is_transient(InjectedPermanentFault("injected"))


# ---------------------------------------------------------------------------
# RetryPolicy.
# ---------------------------------------------------------------------------

def test_retry_transient_fail_n_then_succeed():
    policy = RetryPolicy(max_retries=3, base_delay=0.01)
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert policy.call(flaky, sleep=slept.append) == "ok"
    assert calls["n"] == 3
    assert len(slept) == 2


def test_retry_permanent_raises_immediately():
    policy = RetryPolicy(max_retries=5, base_delay=0.01)
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        policy.call(broken, sleep=lambda d: None)
    assert calls["n"] == 1


def test_retry_exhaustion_reraises_last_error():
    policy = RetryPolicy(max_retries=2, base_delay=0.01)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError(f"attempt {calls['n']}")

    with pytest.raises(OSError, match="attempt 3"):
        policy.call(always, sleep=lambda d: None)
    assert calls["n"] == 3  # 1 try + 2 retries


def test_backoff_is_exponential_jittered_and_seeded():
    policy = RetryPolicy(max_retries=4, base_delay=1.0, multiplier=2.0,
                         jitter=0.25, seed=7)
    a = list(policy.delays("site"))
    b = list(policy.delays("site"))
    assert a == b  # deterministic given (seed, site)
    assert a != list(policy.delays("other-site"))  # sites decorrelate
    assert a != list(RetryPolicy(max_retries=4, base_delay=1.0,
                                 multiplier=2.0, jitter=0.25,
                                 seed=8).delays("site"))
    # Exponential envelope with +-25% jitter around 1, 2, 4, 8.
    for got, nominal in zip(a, (1.0, 2.0, 4.0, 8.0)):
        assert 0.75 * nominal <= got <= 1.25 * nominal
    # And jitter actually moved the values off the nominal schedule.
    assert any(abs(got - nominal) > 1e-6
               for got, nominal in zip(a, (1.0, 2.0, 4.0, 8.0)))


def test_backoff_respects_max_delay():
    policy = RetryPolicy(max_retries=6, base_delay=1.0, multiplier=10.0,
                         max_delay=5.0, jitter=0.0)
    assert max(policy.delays("s")) <= 5.0


def test_on_retry_callback_sees_attempts_and_delays():
    policy = RetryPolicy(max_retries=2, base_delay=0.01)
    seen = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise OSError("x")
        return 42

    policy.call(flaky, sleep=lambda d: None,
                on_retry=lambda exc, attempt, delay: seen.append(
                    (type(exc).__name__, attempt, delay > 0)))
    assert seen == [("OSError", 1, True)]


# ---------------------------------------------------------------------------
# Deadlines.
# ---------------------------------------------------------------------------

def test_run_with_deadline_passes_through_fast_fn():
    assert run_with_deadline(lambda: "done", 5.0, stage="fast") == "done"
    # None / 0 disables the watchdog entirely (inline execution).
    assert run_with_deadline(lambda: "inline", None) == "inline"
    assert run_with_deadline(lambda: "inline", 0) == "inline"


def test_run_with_deadline_raises_on_overrun():
    with pytest.raises(DeadlineExceeded, match="slow-stage"):
        run_with_deadline(lambda: time.sleep(5.0), 0.05, stage="slow-stage")


def test_run_with_deadline_propagates_worker_exception():
    def boom():
        raise KeyError("inner")

    with pytest.raises(KeyError):
        run_with_deadline(boom, 5.0)


def test_cooperative_deadline_check():
    d = Deadline(60.0, stage="long")
    d.check()  # plenty of budget: no raise
    assert d.remaining() > 0
    expired = Deadline(0.0, stage="none")
    with pytest.raises(DeadlineExceeded):
        expired.check()


# ---------------------------------------------------------------------------
# Failure ledger.
# ---------------------------------------------------------------------------

def test_ledger_records_and_persists_atomically(tmp_path):
    out = str(tmp_path)
    ledger = FailureLedger(out)
    ledger.record_retry("ship", "checkpoint.load", OSError("flaky"), 1)
    ledger.record_quarantine("moon", "compute:pregame",
                             ValueError("bad"), attempts=3)
    path = os.path.join(out, resilience.LEDGER_FILENAME)
    assert os.path.exists(path)
    assert not os.path.exists(path + ".tmp")  # atomic: no tmp left behind
    with open(path) as f:
        data = json.load(f)
    assert data["retried"] == {"ship": {"attempts": 1, "incarnation": 0}}
    q = data["quarantined"]["moon"]
    assert q["stage"] == "compute:pregame"
    assert q["attempts"] == 3
    assert q["error_type"] == "ValueError"
    assert q["transient"] is False
    assert q["incarnation"] == 0
    assert bool(ledger)
    assert ledger.words == ["moon"]


def test_ledger_resume_clears_on_success(tmp_path):
    out = str(tmp_path)
    FailureLedger(out).record_quarantine(
        "moon", "study", OSError("x"), attempts=3)
    # A new run loads the prior quarantine state...
    ledger = FailureLedger(out)
    assert "moon" in ledger.quarantined
    # ...and clears it once the word finally succeeds.
    ledger.record_success("moon")
    assert not ledger
    with open(os.path.join(out, resilience.LEDGER_FILENAME)) as f:
        assert json.load(f)["quarantined"] == {}


def test_ledger_quarantines_its_own_corrupt_file(tmp_path):
    path = os.path.join(str(tmp_path), resilience.LEDGER_FILENAME)
    with open(path, "w") as f:
        f.write('{"quarantined": {"moon"')  # torn write
    ledger = FailureLedger(str(tmp_path))
    assert not ledger  # starts clean
    assert os.path.exists(path + ".corrupt")


def test_ledger_merges_retries_across_incarnations(tmp_path):
    """Satellite (ISSUE 5): a resume incarnation preserves prior
    incarnations' retry entries (attributed to the process that saw them)
    while a plain incarnation-0 rerun still resets them."""
    out = str(tmp_path)
    led0 = FailureLedger(out, incarnation=0)
    led0.record_retry("ship", "checkpoint.load", OSError("flaky"), 1)
    led0.record_quarantine("moon", "study", OSError("dead"), attempts=3)

    # Incarnation 1 resumes: prior retry preserved AND attributed; its own
    # events stamp incarnation 1; the prior quarantine clears on success.
    led1 = FailureLedger(out, incarnation=1)
    assert led1.retried == {"ship": {"attempts": 1, "incarnation": 0}}
    assert led1.quarantined["moon"]["incarnation"] == 0
    led1.record_retry("flag", "compute:pregame", OSError("x"), 2)
    led1.record_success("moon")
    data = json.loads(open(os.path.join(out, resilience.LEDGER_FILENAME)).read())
    assert data["incarnation"] == 1
    assert data["retried"] == {
        "ship": {"attempts": 1, "incarnation": 0},
        "flag": {"attempts": 2, "incarnation": 1},
    }
    assert data["quarantined"] == {}

    # A fresh unsupervised rerun (incarnation 0) resets per-run noise.
    led2 = FailureLedger(out, incarnation=0)
    assert led2.retried == {}


def test_ledger_normalizes_v1_int_retry_entries(tmp_path):
    """A v1 ledger (retried: {word: int}) read by a resume incarnation is
    normalized to the stamped form, attributed to the writing run."""
    path = os.path.join(str(tmp_path), resilience.LEDGER_FILENAME)
    with open(path, "w") as f:
        json.dump({"version": 1, "quarantined": {}, "retried": {"ship": 2}}, f)
    led = FailureLedger(str(tmp_path), incarnation=1)
    assert led.retried == {"ship": {"attempts": 2, "incarnation": 0}}


def test_current_incarnation_reads_env(monkeypatch):
    monkeypatch.delenv(resilience.INCARNATION_ENV, raising=False)
    assert resilience.current_incarnation() == 0
    monkeypatch.setenv(resilience.INCARNATION_ENV, "3")
    assert resilience.current_incarnation() == 3
    monkeypatch.setenv(resilience.INCARNATION_ENV, "junk")
    assert resilience.current_incarnation() == 0


# ---------------------------------------------------------------------------
# Fault injector.
# ---------------------------------------------------------------------------

def test_injector_fail_n_then_succeed_schedule():
    inj = FaultInjector()
    inj.arm("checkpoint.read", mode="fail", times=2, match="ship")
    for _ in range(2):
        with pytest.raises(InjectedFault):
            inj.fire("checkpoint.read", word="ship")
    inj.fire("checkpoint.read", word="ship")  # schedule exhausted: no-op
    inj.fire("checkpoint.read", word="moon")  # never matched: no-op


def test_injector_permanent_and_always_fail():
    inj = FaultInjector()
    inj.arm("decode.launch", mode="fail", kind="permanent", times=None)
    for _ in range(3):
        with pytest.raises(InjectedPermanentFault):
            inj.fire("decode.launch")


def test_injector_truncate_write(tmp_path):
    path = str(tmp_path / "artifact.json")
    with open(path, "wb") as f:
        f.write(b"x" * 100)
    inj = FaultInjector()
    inj.arm("cache.write", mode="truncate", times=1)
    inj.fire("cache.write", path=path)
    assert os.path.getsize(path) == 50
    inj.fire("cache.write", path=path)  # exhausted: untouched
    assert os.path.getsize(path) == 50


def test_injector_die_mode_exits_hard(monkeypatch):
    """``die`` calls os._exit (SIGKILL-equivalent) at the matched site —
    monkeypatched here so the test process survives to assert on it."""
    exits = []
    monkeypatch.setattr(resilience.os, "_exit",
                        lambda code: exits.append(code))
    inj = FaultInjector()
    inj.arm("cache.write", mode="die", times=1, match="ship")
    inj.fire("cache.write", word="moon", path="/x/moon.json")   # no match
    assert exits == []
    inj.fire("cache.write", word="ship", path="/x/ship.json")
    assert exits == [resilience.DIE_EXIT_CODE]
    inj.fire("cache.write", word="ship", path="/x/ship.json")   # exhausted
    assert exits == [resilience.DIE_EXIT_CODE]


def test_injector_die_mode_custom_exit_code_via_env_plan(monkeypatch):
    """die is armable via TABOO_FAULT_PLAN like every other mode, with a
    configurable exit status."""
    exits = []
    monkeypatch.setattr(resilience.os, "_exit",
                        lambda code: exits.append(code))
    monkeypatch.setenv("TABOO_FAULT_PLAN", json.dumps(
        {"decode.launch": {"mode": "die", "exit_code": 86}}))
    inj = FaultInjector.from_env()
    inj.fire("decode.launch", rows=4)
    assert exits == [86]


def test_injector_die_mode_kills_a_real_child():
    """End to end in a real subprocess: the armed die site takes the process
    down with the SIGKILL-style status, no cleanup, no traceback."""
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env["TABOO_FAULT_PLAN"] = json.dumps(
        {"cache.write": {"mode": "die", "times": 1}})
    code = ("from taboo_brittleness_tpu.runtime import resilience\n"
            "resilience.fire('cache.write', path='x')\n"
            "print('unreachable')\n")
    proc = subprocess.run([_sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=60,
                          cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert proc.returncode == resilience.DIE_EXIT_CODE
    assert "unreachable" not in proc.stdout


def test_fault_spec_incarnation_scope(monkeypatch):
    """A spec scoped to one incarnation is inert in every other process —
    the cross-incarnation crash-plan mechanism (counters are per-process, so
    'die in incarnation 0, delay in incarnation 1' needs the scope)."""
    inj = FaultInjector()
    inj.arm("checkpoint.read", mode="fail", times=None, incarnation=1)
    monkeypatch.setenv(resilience.INCARNATION_ENV, "0")
    inj.fire("checkpoint.read", word="ship")          # wrong incarnation
    monkeypatch.setenv(resilience.INCARNATION_ENV, "1")
    with pytest.raises(InjectedFault):
        inj.fire("checkpoint.read", word="ship")


def test_injector_rejects_unknown_site_and_mode():
    inj = FaultInjector()
    with pytest.raises(ValueError, match="unknown fault site"):
        inj.arm("no.such.site", mode="fail")
    with pytest.raises(ValueError, match="unknown fault mode"):
        FaultSpec(mode="explode")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="sideways")


def test_injector_from_env_plan(tmp_path, monkeypatch):
    plan = {"checkpoint.read": {"mode": "fail", "times": 1, "match": "ship"},
            "cache.write": [{"mode": "truncate", "times": 2}]}
    # Inline JSON form.
    monkeypatch.setenv("TABOO_FAULT_PLAN", json.dumps(plan))
    inj = FaultInjector.from_env()
    with pytest.raises(InjectedFault):
        inj.fire("checkpoint.read", word="gemma-2-9b-it-taboo-ship")
    # File form.
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        json.dump(plan, f)
    monkeypatch.setenv("TABOO_FAULT_PLAN", plan_path)
    inj2 = FaultInjector.from_env()
    with pytest.raises(InjectedFault):
        inj2.fire("checkpoint.read", word="ship")
    # Unset -> inert injector.
    monkeypatch.delenv("TABOO_FAULT_PLAN")
    assert not FaultInjector.from_env().armed


def test_module_level_fire_is_noop_when_unarmed():
    resilience.fire("decode.launch", rows=3)  # must not raise


# ---------------------------------------------------------------------------
# Quarantine + atomic json helpers.
# ---------------------------------------------------------------------------

def test_quarantine_file_renames_and_tolerates_missing(tmp_path):
    p = str(tmp_path / "entry.json")
    with open(p, "w") as f:
        f.write("{broken")
    dst = resilience.quarantine_file(p, reason="test")
    assert dst == p + ".corrupt"
    assert not os.path.exists(p)
    assert os.path.exists(dst)
    assert resilience.quarantine_file(str(tmp_path / "gone.json")) is None


def test_atomic_json_dump_roundtrip_and_no_tmp(tmp_path):
    p = str(tmp_path / "nested" / "out.json")
    resilience.atomic_json_dump({"a": [1, 2]}, p)
    with open(p) as f:
        assert json.load(f) == {"a": [1, 2]}
    assert not os.path.exists(p + ".tmp")


# ---------------------------------------------------------------------------
# CheckpointManager integration.
# ---------------------------------------------------------------------------

class _FlakyManager:
    """A CheckpointManager with _load_triple stubbed: fail per plan."""

    def __new__(cls, fails_by_word, loaded):
        from taboo_brittleness_tpu.config import ModelConfig
        from taboo_brittleness_tpu.runtime.checkpoints import CheckpointManager

        mgr = CheckpointManager(
            ModelConfig(), retry_policy=RetryPolicy(max_retries=3,
                                                    base_delay=0.0))

        def load_triple(word):
            loaded.append(word)
            remaining = fails_by_word.get(word, 0)
            if remaining:
                fails_by_word[word] = remaining - 1
                raise OSError(f"flaky load of {word}")
            return (f"params-{word}", f"cfg-{word}", f"tok-{word}")

        mgr._load_triple = load_triple
        return mgr


def test_manager_load_retries_transient_errors():
    loaded = []
    mgr = _FlakyManager({"ship": 2}, loaded)
    assert mgr.load("ship")[0] == "params-ship"
    assert loaded == ["ship", "ship", "ship"]


def test_manager_prefetch_error_is_retried_at_load_not_raised():
    """A transient prefetch failure must surface as a retryable load, not
    poison _pending_results (the tentpole's prefetch contract)."""
    loaded = []
    mgr = _FlakyManager({"ship": 1}, loaded)
    mgr.prefetch("ship")
    mgr._pending["ship"].join()
    assert mgr._pending_results["ship"][0] is False
    # load() treats the failed prefetch as attempt 1 and retries.
    assert mgr.load("ship")[0] == "params-ship"
    assert loaded == ["ship", "ship"]
    assert not mgr._pending and not mgr._pending_results


def test_manager_permanent_prefetch_error_still_raises():
    from taboo_brittleness_tpu.config import ModelConfig
    from taboo_brittleness_tpu.runtime.checkpoints import CheckpointManager

    mgr = CheckpointManager(ModelConfig(),
                            retry_policy=RetryPolicy(max_retries=3,
                                                     base_delay=0.0))
    mgr._load_triple = lambda word: (_ for _ in ()).throw(
        FileNotFoundError("no snapshot"))
    mgr.prefetch("ship")
    with pytest.raises(FileNotFoundError):
        mgr.load("ship")


def test_manager_stale_errored_prefetch_does_not_leak_across_sweep():
    """Regression (satellite): a word whose prefetch errored but that was
    never load()ed must not pin its stale error — a later prefetch re-arms
    and a later load succeeds with the fresh result."""
    loaded = []
    mgr = _FlakyManager({"ship": 1}, loaded)
    mgr.prefetch("ship")
    mgr._pending["ship"].join()          # errored, nobody load()s it
    assert mgr._pending_results["ship"][0] is False
    # The sweep skips/quarantines ship, moves on, then a rerun prefetches it
    # again: the stale errored entry must be replaced, not returned early.
    mgr.prefetch("ship")
    mgr._pending["ship"].join()
    assert mgr._pending_results["ship"][0] is True
    assert mgr.load("ship")[0] == "params-ship"
    assert not mgr._pending and not mgr._pending_results


def test_manager_drop_pending_discards_thread_state():
    loaded = []
    mgr = _FlakyManager({"ship": 5}, loaded)
    mgr.prefetch("ship")
    mgr.drop_pending("ship")
    assert not mgr._pending and not mgr._pending_results
    mgr.drop_pending("never-prefetched")  # idempotent / unknown word ok


def test_manager_prefetch_thread_site_is_armable():
    """Arm the 'prefetch.thread' FAULT_SITES entry (the worker-thread site):
    the injected fault fails the prefetch *inside* the worker, and load()
    then retries it like any transient error — proving the schedule reaches
    the thread and the error routes through _pending_results, not a crash."""
    loaded = []
    mgr = _FlakyManager({}, loaded)
    inj = FaultInjector()
    inj.arm("prefetch.thread", mode="fail", times=1, match="ship")
    resilience.set_injector(inj)
    try:
        mgr.prefetch("ship")
        mgr._pending["ship"].join()
        assert mgr._pending_results["ship"][0] is False
        assert isinstance(mgr._pending_results["ship"][1], InjectedFault)
        assert mgr.load("ship")[0] == "params-ship"
        assert loaded == ["ship"]  # attempt 1 was the injected thread fault
    finally:
        resilience.set_injector(None)


def test_manager_load_deadline_classifies_hang_as_transient():
    from taboo_brittleness_tpu.config import ModelConfig
    from taboo_brittleness_tpu.runtime.checkpoints import CheckpointManager

    mgr = CheckpointManager(ModelConfig(), load_deadline=0.05)
    mgr._load_triple = lambda word: time.sleep(5.0)
    with pytest.raises(DeadlineExceeded) as ei:
        mgr.load("ship")
    assert resilience.is_transient(ei.value)
