"""9B-scale converter/loader proof (VERDICT r04 next-round #3).

The real `bcywinski/gemma-2-9b-it-taboo-*` checkpoints cannot download here
(no hub egress), so the on-ramp is proven at full 9B SHAPES with a synthetic
snapshot (tools/synth_checkpoint.py): same 42 x 3584 x 256k bf16 sharded
safetensors layout, streamed through ``models.params`` with bounded peak RSS,
placed per ``parallel.mesh.param_specs`` on a virtual tp=4 mesh, and run
through one AOT-lowered forward chunk.

The tiny-shape test always runs (streamed == whole-dict loader, bit-exact);
the full-scale test is slow (~writes 18.5 GB to disk) and opt-in::

    TBX_9B_IO=1 python -m pytest tests/test_scale9b.py -q
"""

import json
import os
import resource
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.models import gemma2, params as params_mod

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


def _write_snapshot(out_dir, cfg, shard_bytes):
    import synth_checkpoint

    synth_checkpoint.write_snapshot(str(out_dir), cfg,
                                    shard_bytes=shard_bytes)


def test_streamed_loader_matches_whole_dict_loader(tmp_path):
    """Tiny shapes, always on: the leaf-streaming loader must produce the
    same pytree as from_safetensors_dir, and the config round-trips."""
    cfg = gemma2.PRESETS["gemma2_tiny"]
    _write_snapshot(tmp_path, cfg, shard_bytes=16_000)  # force many shards
    files = os.listdir(tmp_path)
    assert "model.safetensors.index.json" in files
    assert sum(f.endswith(".safetensors") for f in files) > 2  # sharded

    inferred = params_mod.infer_config_from_hf_config_json(
        str(tmp_path), dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    assert inferred == cfg

    whole = params_mod.from_safetensors_dir(str(tmp_path), cfg)
    streamed = params_mod.from_safetensors_dir_streamed(str(tmp_path), cfg)
    flat_w = jax.tree_util.tree_leaves_with_path(whole)
    flat_s = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(streamed)}
    assert len(flat_w) == len(flat_s)
    for k, v in flat_w:
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(flat_s[jax.tree_util.keystr(k)]))

    # And the loaded params actually run.
    out = gemma2.forward(streamed, cfg, jnp.asarray([[5, 6, 7]]))
    assert np.isfinite(np.asarray(out.logits)).all()


def test_streamed_loader_places_on_mesh(tmp_path):
    """Tiny shapes on a real (virtual) tp=4 mesh: every leaf lands with its
    param_specs sharding and per-device bytes match the policy."""
    from taboo_brittleness_tpu.config import MeshConfig
    from taboo_brittleness_tpu.parallel import mesh as mesh_mod

    # The tiny preset's deliberately-odd 199 vocab does not divide tp=4;
    # the placement test wants the 9B's divisibility properties at tiny cost.
    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=256)
    _write_snapshot(tmp_path, cfg, shard_bytes=16_000)
    mesh = mesh_mod.make_mesh(MeshConfig(dp=1, tp=4, sp=1),
                              devices=jax.devices()[:4])
    params = params_mod.from_safetensors_dir_streamed(
        str(tmp_path), cfg, mesh=mesh)
    specs = mesh_mod.param_specs(cfg)

    def check(leaf, spec):
        assert leaf.sharding.spec == spec, (leaf.sharding.spec, spec)

    jax.tree_util.tree_map(check, params, specs,
                           is_leaf=lambda x: isinstance(
                               x, jax.sharding.PartitionSpec))
    # embed [V, D] shards over vocab: each device holds V/4 rows.
    shard_shapes = {s.data.shape for s in params["embed"].addressable_shards}
    assert shard_shapes == {(cfg.vocab_size // 4, cfg.hidden_size)}


@pytest.mark.skipif(os.environ.get("TBX_9B_IO") != "1",
                    reason="slow full-9B-shape IO test (~19 GB disk, minutes);"
                           " set TBX_9B_IO=1")
def test_full_9b_shape_stream_place_and_forward(tmp_path):
    """The VERDICT r04 #3 gate: synthesize a full-shape (42 x 3584 x 256k)
    bf16 sharded snapshot, stream it through the loader with bounded peak
    RSS, place per param_specs on a tp=4 mesh, and execute one AOT-lowered
    forward chunk."""
    from taboo_brittleness_tpu.config import MeshConfig
    from taboo_brittleness_tpu.parallel import mesh as mesh_mod

    cfg = gemma2.PRESETS["gemma2_9b"]
    _write_snapshot(tmp_path, cfg, shard_bytes=3.5e9)
    with open(tmp_path / "model.safetensors.index.json") as f:
        total = json.load(f)["metadata"]["total_size"]
    assert total > 18e9  # full 9B bf16 footprint on disk

    mesh = mesh_mod.make_mesh(MeshConfig(dp=1, tp=4, sp=1),
                              devices=jax.devices()[:4])
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    params = params_mod.from_safetensors_dir_streamed(
        str(tmp_path), cfg, mesh=mesh)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    # Bounded staging.  Peak RSS added by the load decomposes into:
    #   (a) the (CPU-)device-resident params — ~18.5 GB across the tp=4
    #       shards (on a real TPU host these bytes live in HBM, not RSS);
    #   (b) the mmap'd checkpoint pages safetensors touches while reading —
    #       up to the full ~18.5 GB on disk, file-backed and evictable, but
    #       counted by ru_maxrss;
    #   (c) the loader's actual staging: ~one stacked leaf at a time
    #       (largest ~4.3 GB).
    # The whole-dict loader would add ANOTHER full anonymous state-dict copy
    # plus its converted copy on top (~37 GB more) — that is the regression
    # this bound catches.
    device_bytes = mesh_mod.per_device_bytes(
        jax.eval_shape(lambda p: p, params), mesh_mod.param_specs(cfg),
        mesh) * 4
    assert device_bytes > 17e9
    ckpt_bytes = total
    added = rss_after - rss_before
    print(f"\n9B load: +{added / 1e9:.1f} GB peak RSS "
          f"(device {device_bytes / 1e9:.1f} + mmap ≤{ckpt_bytes / 1e9:.1f})")
    assert added < device_bytes + ckpt_bytes + 8e9, (
        f"loader staging not bounded: +{added / 1e9:.1f} GB vs "
        f"{device_bytes / 1e9:.1f} GB device + {ckpt_bytes / 1e9:.1f} GB mmap")

    # Per-shard shapes prove real tp placement at 9B scale.
    shard_shapes = {s.data.shape for s in params["embed"].addressable_shards}
    assert shard_shapes == {(cfg.vocab_size // 4, cfg.hidden_size)}
    down_shards = {s.data.shape
                   for s in params["layers"]["down"].addressable_shards}
    assert down_shards == {(cfg.num_layers, cfg.intermediate_size // 4,
                            cfg.hidden_size)}

    # One AOT-lowered forward chunk on the sharded weights.
    ids = jnp.zeros((4, 8), jnp.int32) + 5
    fwd = jax.jit(lambda p, i: gemma2.forward(p, cfg, i).logits)
    lowered = fwd.lower(params, ids)
    compiled = lowered.compile()
    logits = np.asarray(compiled(params, ids))
    assert logits.shape == (4, 8, cfg.vocab_size)
    assert np.isfinite(logits).all()
