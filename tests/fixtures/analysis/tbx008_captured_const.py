"""Seeded TBX008 violations: mutable default + captured jnp constant."""

import jax
import jax.numpy as jnp

_TABLE = jnp.arange(8)


@jax.jit
def lookup(i, extras=[]):     # TBX008: mutable default on a traced function
    del extras
    return _TABLE[i]          # TBX008: module-level jnp constant captured
