"""Seeded TBX006 violations: host RNG / clock inside traced code."""

import random
import time

import numpy as np

import jax


@jax.jit
def noisy(x):
    jitter = random.random()        # TBX006: Python random under trace
    seed = np.random.rand()         # TBX006: numpy RNG under trace
    stamp = time.time()             # TBX006: clock frozen at trace time
    return x * jitter + seed + stamp
