"""A clean module: every rule's hazard class done the right way."""

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

ROW_SPEC = P("dp")                      # declared axis


@partial(jax.jit, static_argnames=("top_k",), donate_argnames=("kv_cache",))
def step(params, kv_cache, x, *, top_k):
    acts = x.astype(jnp.float32)        # tiny [T, k] working buffer
    vals, ids = jax.lax.top_k(acts, top_k)
    return params, kv_cache, vals, ids


def timed():
    t0 = time.monotonic()
    work = sum(range(10))
    return time.monotonic() - t0, work
