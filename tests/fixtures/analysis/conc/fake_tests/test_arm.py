"""Arming corpus for the TBX206 fixture: only demo.read is exercised."""
PLAN = '{"demo.read": {"mode": "fail", "times": 1}}'
