"""TBX205 corpus: bare truncate-write of a durable artifact (hit +
pragma'd) vs the tmp+os.replace protocol and an append-only log (exempt)."""
import json
import os


def bare_write(results, path):
    with open(path, "w") as f:
        json.dump(results, f)


def pragmad_write(rows, path):
    with open(path, "w") as f:  # tbx: TBX205-ok — demo: scratch file
        f.write("\n".join(rows))


def atomic_write(results, path):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(results, f)
    os.replace(tmp, path)


def append_log(line, path):
    with open(path, "a") as f:
        f.write(line + "\n")


def read_back(path):
    with open(path) as f:
        return json.load(f)
