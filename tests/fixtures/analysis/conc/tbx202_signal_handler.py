"""TBX202 corpus: the PR-5 signal-handler self-deadlock shape.

`bad_handler` reaches a lock acquisition through its call graph (the tracer
lock incident); `noted_handler` does I/O under a demo pragma; `good_handler`
only sets a latch (clean twin).
"""
import signal
import threading

_TRACE_LOCK = threading.Lock()
EVENTS = []
DRAIN = threading.Event()


def _emit(name):
    with _TRACE_LOCK:
        EVENTS.append(name)


def bad_handler(signum, frame):
    _emit(f"signal:{signum}")


def noted_handler(signum, frame):
    import sys

    # tbx: TBX202-ok — demo: single fd write, no locks taken
    sys.stderr.write("draining\n")


def good_handler(signum, frame):
    DRAIN.set()


def install():
    signal.signal(signal.SIGTERM, bad_handler)
    signal.signal(signal.SIGINT, noted_handler)
    signal.signal(signal.SIGUSR1, good_handler)
