"""TBX203 corpus: an A->B / B->A lock-order cycle (hit), a second cycle
under a demo pragma, and a consistently ordered pair (clean twin)."""
import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()
_C_LOCK = threading.Lock()
_D_LOCK = threading.Lock()
_E_LOCK = threading.Lock()


def ab():
    with _A_LOCK:
        with _B_LOCK:
            return 1


def ba():
    with _B_LOCK:
        with _A_LOCK:
            return 2


def de():
    with _D_LOCK:
        with _E_LOCK:  # tbx: TBX203-ok — demo: ed() only runs in tests
            return 3


def ed():
    with _E_LOCK:
        with _D_LOCK:
            return 4


def consistent():
    with _A_LOCK:
        with _C_LOCK:
            return 5
