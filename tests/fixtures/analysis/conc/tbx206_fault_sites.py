"""TBX206 corpus: one registry exercising every drift class.  The paired
arming corpus (fake_tests/) mentions only demo.read."""
FAULT_SITES = (
    "demo.read",       # fired + armed: clean
    "demo.write",      # fired, never armed in tests: hit
    "demo.orphan",     # registered, never fired: hit
    "demo.reserved",   # tbx: TBX206-ok — demo: reserved for the next rev
)


def fire(site, **context):
    del site, context


def do_read():
    fire("demo.read")


def do_write():
    fire("demo.write")


def do_rogue():
    fire("demo.rogue")
