"""TBX201 corpus: a daemon counter whose thread and main side share attrs.

`_count` crosses the boundary with no lock (hit); `_safe` is locked on both
sides (clean twin); `Latched._flag` carries the demo pragma.
"""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._count = 0
        self._safe = 0

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            self._count += 1
            with self._lock:
                self._safe += 1

    def read(self):
        with self._lock:
            safe = self._safe
        return self._count + safe

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


class Latched:
    def __init__(self):
        self._thread = None
        self._flag = 0

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        self._flag = 1  # tbx: TBX201-ok — one-shot monotonic latch (demo)

    def done(self):
        return self._flag == 1

    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
