"""TBX204 corpus: the PR-2 fire-and-forget leak shape (hit + pragma'd), and
the three sanctioned lifecycles — direct join, dict-of-handles join (the
fixed prefetch form), and the swap-then-join stop idiom."""
import threading


def leak_fire_and_forget(fn):
    threading.Thread(target=fn, daemon=True).start()


def leak_with_pragma(fn):
    # tbx: TBX204-ok — demo: watchdog may outlive its owner by design
    threading.Thread(target=fn, daemon=True).start()


def joined(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class Prefetcher:
    """The PR-2 shape, fixed form: handles kept and joined at load."""

    def __init__(self):
        self._pending = {}

    def prefetch(self, word, fn):
        t = threading.Thread(target=fn, name=f"prefetch-{word}", daemon=True)
        self._pending[word] = t
        t.start()

    def load(self, word):
        self._pending.pop(word).join()


class Stoppable:
    def __init__(self):
        self._thread = None

    def start(self, fn):
        self._thread = threading.Thread(target=fn)
        self._thread.start()

    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
