"""Seeded TBX004 violations: static_argnames naming absent parameters."""

from functools import partial

import jax


@partial(jax.jit, static_argnames=("cfg", "topk"))   # TBX004: 'topk' absent
def readout(params, cfg, x, *, top_k):
    del cfg, top_k
    return params, x


def _scorer(x, chunk):
    del chunk
    return x


scorer_jit = jax.jit(_scorer, static_argnames=("chunks",))  # TBX004: 'chunks'
