"""TBX010 corpus: registered jit entry points dispatched with no
TraceAnnotation/named_scope wrapper.

The rule is PATH-scoped (only ``taboo_brittleness_tpu/`` outside
``analysis/``), so tests scan this file under a package-relative ``rel``
alias — see tests/test_analysis.py::test_tbx010_fixture_and_path_scope.
"""

import jax

from taboo_brittleness_tpu import obs
from taboo_brittleness_tpu.runtime.decode import greedy_decode


def bad_dispatch(params, cfg, ids, valid, pos):
    return greedy_decode(params, cfg, ids, valid, pos, max_new_tokens=4)


def good_dispatch(params, cfg, ids, valid, pos):
    with obs.profile.annotate("decode", fn=greedy_decode):
        return greedy_decode(params, cfg, ids, valid, pos, max_new_tokens=4)


def good_raw_annotation(params, cfg, ids, valid, pos):
    with jax.profiler.TraceAnnotation("tbx:decode#0"):
        return greedy_decode(params, cfg, ids, valid, pos, max_new_tokens=4)


def reviewed_dispatch(params, cfg, ids, valid, pos):
    # tbx: TBX010-ok — warm-up call, device time is deliberately anonymous
    return greedy_decode(params, cfg, ids, valid, pos, max_new_tokens=4)


@jax.jit
def traced_caller(params, cfg, ids, valid, pos):
    # Under trace this is inlining, not a dispatch site: never flagged.
    return greedy_decode(params, cfg, ids, valid, pos, max_new_tokens=4)
