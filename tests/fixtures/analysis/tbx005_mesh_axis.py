"""Seeded TBX005 violations: axis strings not declared in parallel/mesh.py."""

from jax import lax
from jax.sharding import PartitionSpec as P

BAD_SPEC = P("dp", "model")        # TBX005: 'model' is not a declared axis
GOOD_SPEC = P("dp", "tp")          # declared axes: fine


def local_sum(x):
    return lax.psum(x, axis_name="rows")   # TBX005: 'rows' undeclared
