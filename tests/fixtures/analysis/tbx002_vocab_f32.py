"""Seeded TBX002 violation: f32 materialization of a vocab-scale array."""

import jax.numpy as jnp


def readout(h, embed):
    logits = h @ embed.T                       # [B, T, V] bf16
    probs = logits.astype(jnp.float32)         # TBX002: vocab-carrying f32
    big = (h @ embed.T).astype(jnp.float32)    # [B, T, V] shape-comment hint
    return probs, big


def fine(x):
    return x.astype(jnp.float32)               # no vocab signal: not flagged
