"""Seeded TBX007 violations: wall clock used for duration math."""

import dataclasses
import time


def timed_work():
    t0 = time.time()                  # TBX007: start mark on the wall clock
    work = sum(range(10))
    return time.time() - t0, work     # TBX007: duration by subtraction


@dataclasses.dataclass
class Record:
    started: float = dataclasses.field(default_factory=time.time)  # TBX007
