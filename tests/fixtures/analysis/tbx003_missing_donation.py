"""Seeded TBX003 violation: a KV-cache-carrying jit that donates nothing."""

from functools import partial

import jax


@partial(jax.jit, static_argnames=("steps",))
def step_with_cache(params, kv_cache, *, steps):   # TBX003 at the decorator
    del steps
    return params, kv_cache
