"""TBX009 corpus: bare print() in package code.

The rule is PATH-scoped (only ``taboo_brittleness_tpu/`` outside
``analysis/``), so tests scan this file under a package-relative ``rel``
alias — see tests/test_analysis.py::test_tbx009_fixture_and_path_scope.
"""


def sweep_step(word):
    print(f"starting {word}")
    print("done", word)


def cli_summary(results):
    print(results)  # tbx: TBX009-ok — reviewed stdout contract
