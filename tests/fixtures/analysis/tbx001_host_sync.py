"""Seeded TBX001 violations: host syncs reachable from a jit trace root.

This file is the checker's corpus (tests/test_analysis.py asserts the exact
codes and line numbers) — it is excluded from the repo gate by default and
never imported.
"""

import jax
import numpy as np


def _pull_helper(x):
    return np.asarray(x).sum()          # TBX001: np.asarray in traced reach


@jax.jit
def traced(x):
    y = jax.device_get(x)               # TBX001: device_get under trace
    z = x.sum().item()                  # TBX001: .item() under trace
    return _pull_helper(x) + y + z
