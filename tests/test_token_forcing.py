"""Token forcing pre/postgame on the tiny model (paper §D.4–D.5 mechanics)."""

import pytest

import jax

from taboo_brittleness_tpu.config import Config, ExperimentConfig, ModelConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.pipelines import token_forcing as tf
from taboo_brittleness_tpu.runtime import chat
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(21), cfg)
    words = [WORD, "secret", "word", "is", "My", "hint", "Give", "me", "a"]
    tok = WordTokenizer(words, vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=1, top_k=2, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=4),
        word_plurals={WORD: [WORD, WORD + "s"]},
        prompts=["Give me a hint"],
    )
    return params, cfg, tok, config


def test_pregame_covers_all_prefills(setup):
    params, cfg, tok, config = setup
    res = tf.pregame_forcing(params, cfg, tok, config, WORD)
    assert res["mode"] == "pregame"
    n = len(config.token_forcing.prefill_phrases)
    assert len(res["completions"]) == n
    for phrase, comp in zip(config.token_forcing.prefill_phrases, res["completions"]):
        assert comp.startswith(phrase)
    assert 0.0 <= res["success_rate"] <= 1.0


def test_postgame_builds_warmup_transcript(setup):
    params, cfg, tok, config = setup
    res = tf.postgame_forcing(params, cfg, tok, config, WORD)
    transcript = res["warmup_transcript"]
    user_turns = [t for t in transcript if t["role"] == "user"]
    model_turns = [t for t in transcript if t["role"] == "model"]
    # 3 warmup user turns + final adversarial turn; a model reply per warmup
    assert [t["content"] for t in user_turns[:3]] == list(
        config.token_forcing.warmup_prompts)
    assert user_turns[3]["content"] == config.token_forcing.final_prompt
    assert len(model_turns) == 3
    for t in model_turns:
        assert chat.END_OF_TURN not in t["content"]
    assert len(res["completions"]) == len(config.token_forcing.prefill_phrases)


def test_run_token_forcing_overall(setup, tmp_path):
    params, cfg, tok, config = setup
    out = str(tmp_path / "forcing.json")
    res = tf.run_token_forcing(
        config, model_loader=lambda w: (params, cfg, tok),
        words=[WORD], modes=("pregame",), output_path=out)
    assert "pregame" in res["overall"]
    assert res["words"][WORD]["pregame"]["word"] == WORD
    import json, os
    assert os.path.exists(out)
    with open(out) as f:
        assert json.load(f)["overall"] == res["overall"]


def test_forcing_success_detects_leak(setup):
    from taboo_brittleness_tpu import metrics as m
    assert m.forcing_success(["My secret word is moon!"], {"moon", "moons"}) == 1.0
    assert m.forcing_success(["I cannot tell you"], {"moon", "moons"}) == 0.0
    # word-boundary: "moonlight" is not a leak
    assert m.forcing_success(["moonlight"], {"moon"}) == 0.0
