"""Token forcing pre/postgame on the tiny model (paper §D.4–D.5 mechanics)."""

import pytest

import jax

from taboo_brittleness_tpu.config import Config, ExperimentConfig, ModelConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.pipelines import token_forcing as tf
from taboo_brittleness_tpu.runtime import chat
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(21), cfg)
    words = [WORD, "secret", "word", "is", "My", "hint", "Give", "me", "a"]
    tok = WordTokenizer(words, vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=1, top_k=2, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=4),
        word_plurals={WORD: [WORD, WORD + "s"]},
        prompts=["Give me a hint"],
    )
    return params, cfg, tok, config


def test_pregame_covers_all_prefills(setup):
    params, cfg, tok, config = setup
    res = tf.pregame_forcing(params, cfg, tok, config, WORD)
    assert res["mode"] == "pregame"
    n = len(config.token_forcing.prefill_phrases)
    assert len(res["completions"]) == n
    for phrase, comp in zip(config.token_forcing.prefill_phrases, res["completions"]):
        assert comp.startswith(phrase)
    assert 0.0 <= res["success_rate"] <= 1.0


def test_postgame_builds_warmup_transcript(setup):
    params, cfg, tok, config = setup
    res = tf.postgame_forcing(params, cfg, tok, config, WORD)
    transcript = res["warmup_transcript"]
    user_turns = [t for t in transcript if t["role"] == "user"]
    model_turns = [t for t in transcript if t["role"] == "model"]
    # 3 warmup user turns + final adversarial turn; a model reply per warmup
    assert [t["content"] for t in user_turns[:3]] == list(
        config.token_forcing.warmup_prompts)
    assert user_turns[3]["content"] == config.token_forcing.final_prompt
    assert len(model_turns) == 3
    for t in model_turns:
        assert chat.END_OF_TURN not in t["content"]
    assert len(res["completions"]) == len(config.token_forcing.prefill_phrases)


def test_run_token_forcing_overall(setup, tmp_path):
    params, cfg, tok, config = setup
    out = str(tmp_path / "forcing.json")
    res = tf.run_token_forcing(
        config, model_loader=lambda w: (params, cfg, tok),
        words=[WORD], modes=("pregame",), output_path=out)
    assert "pregame" in res["overall"]
    assert res["words"][WORD]["pregame"]["word"] == WORD
    import json, os
    assert os.path.exists(out)
    with open(out) as f:
        assert json.load(f)["overall"] == res["overall"]


def test_run_token_forcing_resumable(setup, tmp_path):
    """Kill/resume: per-word results persist atomically as soon as they exist,
    and a resumed sweep skips completed words without reloading their models
    (VERDICT round-3 item 8)."""
    import json
    import os

    params, cfg, tok, config = setup
    out = str(tmp_path / "forcing.json")
    words_dir = str(tmp_path / "words")
    loads = []

    class Crash(RuntimeError):
        pass

    def crashing_loader(w):
        loads.append(w)
        if w == "word2":
            raise Crash("killed mid-sweep")  # word 2 of 2 dies
        return params, cfg, tok

    config2 = Config(
        model=config.model, experiment=config.experiment,
        word_plurals={WORD: [WORD], "word2": ["word2"]},
        prompts=config.prompts, token_forcing=config.token_forcing)
    # fail_fast=True: this test simulates a hard mid-sweep CRASH (process
    # death), so the failure must propagate; the default retry+quarantine
    # path is covered by tests/test_sweep_resilience.py.
    with pytest.raises(Crash):
        tf.run_token_forcing(
            config2, model_loader=crashing_loader, words=[WORD, "word2"],
            modes=("pregame",), output_path=out, output_dir=words_dir,
            fail_fast=True)
    # The completed word's JSON survived the crash; the aggregate did not
    # (it writes last) — but nothing is truncated/corrupt.
    assert os.path.exists(os.path.join(words_dir, f"{WORD}.json"))
    assert not os.path.exists(out)
    with open(os.path.join(words_dir, f"{WORD}.json")) as f:
        saved = json.load(f)
    assert saved["pregame"]["word"] == WORD

    # Resume: the finished word is NOT reloaded; only word2 runs.
    loads.clear()

    def loader(w):
        loads.append(w)
        return params, cfg, tok

    res = tf.run_token_forcing(
        config2, model_loader=loader, words=[WORD, "word2"],
        modes=("pregame",), output_path=out, output_dir=words_dir)
    assert loads == ["word2"]
    assert res["words"][WORD] == saved
    assert os.path.exists(out)
    assert set(res["words"]) == {WORD, "word2"}

    # A saved entry from a NARROWER modes run does not count as done: asking
    # for pregame+postgame re-measures the word instead of crashing on the
    # missing mode at aggregation.
    loads.clear()
    res2 = tf.run_token_forcing(
        config2, model_loader=loader, words=[WORD],
        modes=("pregame", "postgame"), output_path=out, output_dir=words_dir)
    assert loads == [WORD]
    assert set(res2["words"][WORD]) == {"pregame", "postgame"}
    assert set(res2["overall"]) == {"pregame", "postgame"}
    # And the widened entry now satisfies a narrower resume.
    loads.clear()
    res3 = tf.run_token_forcing(
        config2, model_loader=loader, words=[WORD],
        modes=("pregame",), output_path=out, output_dir=words_dir)
    assert loads == []
    assert res3["words"][WORD]["pregame"] == res2["words"][WORD]["pregame"]


def test_forcing_success_detects_leak(setup):
    from taboo_brittleness_tpu import metrics as m
    assert m.forcing_success(["My secret word is moon!"], {"moon", "moons"}) == 1.0
    assert m.forcing_success(["I cannot tell you"], {"moon", "moons"}) == 0.0
    # word-boundary: "moonlight" is not a leak
    assert m.forcing_success(["moonlight"], {"moon"}) == 0.0


def test_forcing_under_arms_identity_matches_unedited(setup):
    """Batched per-arm forcing: the identity arm (all -1 latent ids) must
    reproduce the plain unedited attacks exactly, and a real ablation arm in
    the same batch must score independently (round-3: one batched attack set
    replaces 11 sequential per-arm forcing runs)."""
    import dataclasses as dc

    import jax.numpy as jnp
    import numpy as np

    from taboo_brittleness_tpu.config import TokenForcingConfig
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines.interventions import sae_ablation_edit

    params, cfg, tok, config = setup
    config = dc.replace(config, token_forcing=TokenForcingConfig(
        prefill_phrases=("My secret word is", "The word is"),
        warmup_prompts=("Give me a hint",)))
    sae = sae_ops.init_random(jax.random.PRNGKey(4), cfg.hidden_size, 16)

    plain_pre = tf.pregame_forcing(params, cfg, tok, config, WORD)
    plain_post = tf.postgame_forcing(params, cfg, tok, config, WORD)

    res = tf.forcing_under_arms(
        params, cfg, tok, config, WORD, sae_ablation_edit,
        {"sae": sae, "layer": config.model.layer_idx},
        {"latent_ids": jnp.asarray(
            np.asarray([[-1, -1], [2, 7]]), jnp.int32)})
    assert len(res) == 2
    assert res[0]["pregame"] == plain_pre["success_rate"]
    assert res[0]["postgame"] == plain_post["success_rate"]
    for arm in res:
        assert 0.0 <= arm["pregame"] <= 1.0
        assert 0.0 <= arm["postgame"] <= 1.0


def test_run_token_forcing_memoizes_shared_model(setup, monkeypatch, tmp_path):
    """A shared-model loader pays ONE set of forcing launches for the whole
    word list (the decodes are word-independent given the model; VERDICT
    r04 #8): 1 pregame + 3 warm-up + 1 final decode total, not per word.
    A fresh params object (real per-word checkpoints) must recompute."""
    import jax

    from taboo_brittleness_tpu.config import Config
    from taboo_brittleness_tpu.models import gemma2

    params, cfg, tok, config = setup
    config2 = Config(
        model=config.model, experiment=config.experiment,
        word_plurals={WORD: [WORD], "word2": ["word2"], "word3": ["word3"]},
        prompts=config.prompts, token_forcing=config.token_forcing)

    calls = []
    real = tf._decode_rendered

    def counting(params_, cfg_, tok_, rendered, **kw):
        calls.append(len(rendered))
        return real(params_, cfg_, tok_, rendered, **kw)

    monkeypatch.setattr(tf, "_decode_rendered", counting)

    res = tf.run_token_forcing(
        config2, model_loader=lambda w: (params, cfg, tok),
        words=[WORD, "word2", "word3"], modes=("pregame", "postgame"))
    n_warmup = len(config.token_forcing.warmup_prompts)
    n_phrases = len(config.token_forcing.prefill_phrases)
    # One launch set for 3 words: pregame batch + per-turn warm-ups + final.
    assert calls == [n_phrases] + [1] * n_warmup + [n_phrases]
    # Scoring is still per word (same completions, different valid forms).
    assert set(res["words"]) == {WORD, "word2", "word3"}
    assert (res["words"][WORD]["pregame"]["completions"]
            == res["words"]["word2"]["pregame"]["completions"])

    # A DIFFERENT params object invalidates the memo.
    calls.clear()
    params2 = gemma2.init_params(jax.random.PRNGKey(99), cfg)
    loaders = {WORD: params, "word2": params2}
    tf.run_token_forcing(
        config2, model_loader=lambda w: (loaders[w], cfg, tok),
        words=[WORD, "word2"], modes=("pregame",))
    assert calls == [n_phrases, n_phrases]
