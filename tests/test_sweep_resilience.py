"""End-to-end fault-tolerant sweeps on the tiny model (ISSUE 2 acceptance):
with faults armed on 3/20 words (2 transient, 1 permanent) the sweep must
complete the other 19, quarantine exactly the permanent failure with an
accurate ``_failures.json``, exit non-zero at the CLI, and resume the done
words on rerun without recomputation."""

import json
import os

import numpy as np
import pytest

import jax

from taboo_brittleness_tpu import cli
from taboo_brittleness_tpu.config import Config, ExperimentConfig, ModelConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.pipelines import generation
from taboo_brittleness_tpu.pipelines import token_forcing as tf
from taboo_brittleness_tpu.pipelines.word_sweep import run_word_sweep
from taboo_brittleness_tpu.runtime import cache as cache_io
from taboo_brittleness_tpu.runtime import resilience
from taboo_brittleness_tpu.runtime.resilience import FaultInjector, RetryPolicy
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORDS = [f"w{i:02d}" for i in range(20)]
TRANSIENT = ["w03", "w11"]
PERMANENT = "w07"

# No-sleep policy: the schedules are still real (seeded, exponential), the
# tests just never wait them out.
FAST = RetryPolicy(max_retries=2, base_delay=0.0)


@pytest.fixture(autouse=True)
def _clean_injector():
    resilience.set_injector(FaultInjector())
    yield
    resilience.set_injector(FaultInjector())


@pytest.fixture(scope="module")
def tiny():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(11), cfg)
    tok = WordTokenizer(WORDS + ["secret", "word", "is", "My", "hint"],
                        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=1, top_k=2, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=4),
        word_plurals={w: [w] for w in WORDS},
        prompts=["Give me a hint"],
    )
    return params, cfg, tok, config


def _arm_issue_faults():
    """2 words fail twice transiently (recover within max_retries=2), one
    word fails permanently — armed at the checkpoint.read site."""
    inj = FaultInjector()
    for w in TRANSIENT:
        inj.arm("checkpoint.read", mode="fail", times=2, match=w)
    inj.arm("checkpoint.read", mode="fail", kind="permanent", times=None,
            match=PERMANENT)
    resilience.set_injector(inj)
    return inj


def _counting_loader(tiny, loads):
    params, cfg, tok, _ = tiny

    def loader(word):
        loads.append(word)
        resilience.fire("checkpoint.read", word=word)
        return params, cfg, tok

    return loader


def test_word_sweep_retries_quarantines_and_resumes(tiny, tmp_path):
    """The acceptance scenario, driven through run_token_forcing (the real
    run_word_sweep consumer)."""
    params, cfg, tok, config = tiny
    out_dir = str(tmp_path / "words")
    loads = []
    _arm_issue_faults()

    res = tf.run_token_forcing(
        config, model_loader=_counting_loader(tiny, loads), words=WORDS,
        modes=("pregame",), output_dir=out_dir, retry_policy=FAST)

    # 19 words completed, the permanent failure quarantined.
    done = set(res["words"])
    assert done == set(WORDS) - {PERMANENT}
    for w in done:
        assert os.path.exists(os.path.join(out_dir, f"{w}.json"))
    assert not os.path.exists(os.path.join(out_dir, f"{PERMANENT}.json"))

    # The transient words were retried to success (2 failures + 1 success
    # each); the permanent word failed FAST — one attempt, no retries (a
    # missing shard stays missing; burning the backoff budget on it would
    # just slow the sweep down).
    assert loads.count(TRANSIENT[0]) == 3
    assert loads.count(TRANSIENT[1]) == 3
    assert loads.count(PERMANENT) == 1

    # _failures.json is accurate.
    with open(os.path.join(out_dir, resilience.LEDGER_FILENAME)) as f:
        ledger = json.load(f)
    assert set(ledger["quarantined"]) == {PERMANENT}
    entry = ledger["quarantined"][PERMANENT]
    assert entry["stage"] == "checkpoint.load"
    assert entry["attempts"] == 1
    assert entry["error_type"] == "InjectedPermanentFault"
    assert entry["transient"] is False
    assert set(ledger["retried"]) == set(TRANSIENT)
    assert res["failures"]["quarantined"].keys() == {PERMANENT}

    # overall aggregates the words that finished (not NaN, not crash).
    assert 0.0 <= res["overall"]["pregame"] <= 1.0

    # Rerun with faults cleared: the 19 done words resume WITHOUT
    # recomputation (their models never load), the quarantined word runs
    # and its ledger entry clears.
    resilience.set_injector(FaultInjector())
    loads.clear()
    res2 = tf.run_token_forcing(
        config, model_loader=_counting_loader(tiny, loads), words=WORDS,
        modes=("pregame",), output_dir=out_dir, retry_policy=FAST)
    assert loads == [PERMANENT]
    assert set(res2["words"]) == set(WORDS)
    assert "failures" not in res2
    with open(os.path.join(out_dir, resilience.LEDGER_FILENAME)) as f:
        assert json.load(f)["quarantined"] == {}


def test_word_sweep_fail_fast_aborts_on_first_quarantine(tiny, tmp_path):
    params, cfg, tok, config = tiny
    _arm_issue_faults()
    with pytest.raises(resilience.InjectedPermanentFault):
        tf.run_token_forcing(
            config, model_loader=_counting_loader(tiny, []), words=WORDS,
            modes=("pregame",), output_dir=str(tmp_path / "words"),
            retry_policy=FAST, fail_fast=True)


def test_corrupt_word_json_is_quarantined_and_recomputed(tiny, tmp_path):
    """Satellite: a truncated <word>.json must read as not-done (quarantined
    to *.corrupt, warned, recomputed) instead of raising JSONDecodeError."""
    params, cfg, tok, config = tiny
    out_dir = str(tmp_path / "words")
    words = WORDS[:3]
    loader = _counting_loader(tiny, [])
    tf.run_token_forcing(config, model_loader=loader, words=words,
                         modes=("pregame",), output_dir=out_dir,
                         retry_policy=FAST)

    # Tear one word's resume file.
    torn = os.path.join(out_dir, f"{words[1]}.json")
    with open(torn, "w") as f:
        f.write('{"pregame": {"succ')

    loads = []
    res = tf.run_token_forcing(
        config, model_loader=_counting_loader(tiny, loads), words=words,
        modes=("pregame",), output_dir=out_dir, retry_policy=FAST)
    assert loads == [words[1]]                      # only the torn word reran
    assert os.path.exists(torn + ".corrupt")        # original preserved
    assert set(res["words"]) == set(words)
    with open(torn) as f:
        assert "pregame" in json.load(f)            # recomputed cleanly


def test_run_word_sweep_outcome_contract(tiny, tmp_path):
    """run_word_sweep itself returns partial results + the ledger."""
    params, cfg, tok, config = tiny
    _arm_issue_faults()
    outcome = run_word_sweep(
        config, model_loader=_counting_loader(tiny, []), words=WORDS,
        modes=("m",),
        compute_mode=lambda p, c, t, cf, m: "payload",
        score_word=lambda cf, w, m, payload: {"word": w},
        output_dir=str(tmp_path / "words"), retry_policy=FAST)
    assert not outcome.ok
    assert set(outcome.results) == set(WORDS) - {PERMANENT}
    assert set(outcome.quarantined) == {PERMANENT}


def test_generation_quarantines_and_resumes_with_validated_cache(
        tiny, tmp_path):
    """run_generation: permanent checkpoint fault -> word quarantined, grid
    continues; a truncated summary npz is quarantined on resume and ONLY
    that cell recomputes."""
    params, cfg, tok, config = tiny
    processed = str(tmp_path / "processed")
    words = WORDS[:4]
    inj = FaultInjector()
    inj.arm("checkpoint.read", mode="fail", kind="permanent", times=None,
            match=words[2])
    resilience.set_injector(inj)

    done = generation.run_generation(
        config, model_loader=_counting_loader(tiny, []), words=words,
        processed_dir=processed, retry_policy=FAST)
    assert set(done) == set(words) - {words[2]}
    with open(os.path.join(processed, resilience.LEDGER_FILENAME)) as f:
        assert set(json.load(f)["quarantined"]) == {words[2]}

    # Truncate one finished cell's summary npz (torn write simulation).
    spath = cache_io.summary_path(processed, words[0], 0)
    size = os.path.getsize(spath)
    with open(spath, "r+b") as f:
        f.truncate(size // 2)

    resilience.set_injector(FaultInjector())
    done2 = generation.run_generation(
        config, model_loader=_counting_loader(tiny, []), words=words,
        processed_dir=processed, retry_policy=FAST)
    # The torn cell (and the quarantined word's cells) recomputed; every
    # other cell resumed.
    assert done2[words[0]] == [0]
    assert done2[words[2]] == [0]
    assert done2[words[1]] == []
    assert os.path.exists(spath + ".corrupt")
    assert cache_io.verify_summary(spath)


def test_truncate_fault_plus_validated_resume_roundtrip(tiny, tmp_path):
    """Arm the cache.write truncate fault: the torn artifact is caught by
    the validated resume (quarantined + recomputed), closing the loop
    between the injector and the resume story."""
    params, cfg, tok, config = tiny
    processed = str(tmp_path / "processed")
    inj = FaultInjector()
    inj.arm("cache.write", mode="truncate", times=1)
    resilience.set_injector(inj)
    generation.run_generation(
        config, model_loader=_counting_loader(tiny, []), words=WORDS[:1],
        processed_dir=processed, retry_policy=FAST)
    spath = cache_io.summary_path(processed, WORDS[0], 0)
    assert os.path.exists(spath)

    resilience.set_injector(FaultInjector())
    done = generation.run_generation(
        config, model_loader=_counting_loader(tiny, []), words=WORDS[:1],
        processed_dir=processed, retry_policy=FAST)
    assert done[WORDS[0]] == [0]                     # recomputed, not trusted
    assert os.path.exists(spath + ".corrupt")
    arrays, meta = cache_io.load_summary(spath)      # the fresh cell loads
    assert meta["word"] == WORDS[0]
    assert arrays["target_prob"].dtype == np.float32


def test_cache_write_leaves_no_tmp_files(tiny, tmp_path):
    """Satellite: save_pair / save_summary are tmp+rename atomic."""
    params, cfg, tok, config = tiny
    processed = str(tmp_path / "processed")
    # Summary first (a pre-existing pair would satisfy the summary-mode
    # cache check and skip the summary write), parity pair second.
    generation.generate_for_word(
        params, cfg, tok, config, WORDS[0], processed_dir=processed)
    generation.generate_for_word(
        params, cfg, tok, config, WORDS[0],
        processed_dir=processed, parity_dump=True)
    leftovers = [
        os.path.join(root, name)
        for root, _, names in os.walk(processed)
        for name in names if ".tmp" in name
    ]
    assert leftovers == []
    # And both artifact forms verify.
    assert cache_io.verify_pair(processed, WORDS[0], 0)
    assert cache_io.verify_summary(cache_io.summary_path(processed, WORDS[0], 0))


def test_intervention_studies_quarantine_and_continue(tiny, tmp_path):
    """The studies driver (its own loop, not run_word_sweep) shares the
    retry/quarantine contract: one permanently failing word is ledgered and
    the study continues; the rerun resumes the finished words."""
    import dataclasses as dc

    from taboo_brittleness_tpu.config import InterventionConfig
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv

    params, cfg, tok, config = tiny
    config2 = dc.replace(
        config,
        intervention=InterventionConfig(budgets=(1,), random_trials=1,
                                        ranks=(1,), spike_top_k=2))
    sae = sae_ops.init_random(jax.random.PRNGKey(5), cfg.hidden_size, 16)
    out_dir = str(tmp_path / "studies")
    words = [WORDS[0], PERMANENT, WORDS[2]]
    inj = FaultInjector()
    inj.arm("checkpoint.read", mode="fail", kind="permanent", times=None,
            match=PERMANENT)
    resilience.set_injector(inj)

    loads = []
    out = iv.run_intervention_studies(
        config2, model_loader=_counting_loader(tiny, loads), sae=sae,
        words=words, output_dir=out_dir, retry_policy=FAST)
    assert set(out) == {WORDS[0], WORDS[2]}
    for w in (WORDS[0], WORDS[2]):
        assert os.path.exists(os.path.join(out_dir, f"{w}.json"))
    with open(os.path.join(out_dir, resilience.LEDGER_FILENAME)) as f:
        ledger = json.load(f)
    assert set(ledger["quarantined"]) == {PERMANENT}

    # Rerun, faults cleared: done words resume without loading their models.
    resilience.set_injector(FaultInjector())
    loads.clear()
    out2 = iv.run_intervention_studies(
        config2, model_loader=_counting_loader(tiny, loads), sae=sae,
        words=words, output_dir=out_dir, retry_policy=FAST)
    assert loads == [PERMANENT]
    assert set(out2) == set(words)
    with open(os.path.join(out_dir, resilience.LEDGER_FILENAME)) as f:
        assert json.load(f)["quarantined"] == {}


def test_cli_token_forcing_exits_nonzero_on_quarantine(tiny, tmp_path,
                                                       monkeypatch):
    """The CLI contract: exit code is non-zero iff words were quarantined,
    and the run manifest carries the failures/retries blocks."""
    params, cfg, tok, config = tiny
    _arm_issue_faults()
    monkeypatch.setattr(cli, "_load", lambda args: config)
    monkeypatch.setattr(cli, "_mesh", lambda c: None)
    monkeypatch.setattr(cli, "_loader",
                        lambda c, a, mesh=None: _counting_loader(tiny, []))

    # Inject the no-sleep policy so the CLI run retries without waiting out
    # real backoff delays (everything else flows through the real pipeline).
    orig_tf = tf.run_token_forcing

    def fast_tf(*a, **kw):
        kw.setdefault("retry_policy", FAST)
        return orig_tf(*a, **kw)

    monkeypatch.setattr(tf, "run_token_forcing", fast_tf)
    monkeypatch.chdir(tmp_path)

    rc = cli.main(["token-forcing", "--modes", "pregame",
                   "--words", *WORDS])
    assert rc == 1
    with open(tmp_path / "results" / "token_forcing" / "run_manifest.json") as f:
        manifest = json.load(f)
    assert set(manifest["failures"]) == {PERMANENT}
    assert set(manifest["retries"]) == set(TRANSIENT)

    # Rerun with no faults resumes and exits 0.
    resilience.set_injector(FaultInjector())
    assert cli.main(["token-forcing", "--modes", "pregame",
                     "--words", *WORDS]) == 0
