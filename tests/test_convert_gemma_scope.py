"""tools/convert_gemma_scope.py on synthetic state dicts in every supported
source form (the real release is unreachable without hub egress; the layout —
params.npz with W_enc/W_dec/b_enc/b_dec/threshold — is fixed by the official
gemma-scope release the reference consumes, src/02_run_sae_baseline.py:30-36)."""

import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from taboo_brittleness_tpu.ops import sae as sae_ops

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import convert_gemma_scope as cgs  # noqa: E402

D, S = 8, 32


def _state(rng):
    return {
        "W_enc": rng.normal(size=(D, S)).astype(np.float32),
        "b_enc": rng.normal(size=(S,)).astype(np.float32),
        "W_dec": rng.normal(size=(S, D)).astype(np.float32),
        "b_dec": rng.normal(size=(D,)).astype(np.float32),
        "threshold": rng.random(S).astype(np.float32),
    }


def test_convert_npz_roundtrip(tmp_path):
    state = _state(np.random.default_rng(0))
    src = tmp_path / "params.npz"
    np.savez(src, **state)
    out = tmp_path / "out.npz"
    cgs.convert(str(src), str(out))
    sae = sae_ops.load(str(out))
    np.testing.assert_allclose(np.asarray(sae.w_enc), state["W_enc"])
    np.testing.assert_allclose(np.asarray(sae.threshold), state["threshold"])
    # Loaded SAE actually encodes.
    x = jnp.asarray(np.random.default_rng(1).normal(size=(3, D)), jnp.float32)
    assert sae_ops.encode(sae, x).shape == (3, S)


def test_convert_snapshot_dir_locates_sae_id(tmp_path):
    state = _state(np.random.default_rng(2))
    sae_dir = tmp_path / "layer_31" / "width_16k" / "average_l0_76"
    sae_dir.mkdir(parents=True)
    np.savez(sae_dir / "params.npz", **state)
    out = tmp_path / "out.npz"
    cgs.convert(str(tmp_path), str(out),
                sae_id="layer_31/width_16k/average_l0_76")
    np.testing.assert_allclose(
        np.asarray(sae_ops.load(str(out)).w_dec), state["W_dec"])


def test_convert_fixes_transposed_encoder(tmp_path):
    state = _state(np.random.default_rng(3))
    flipped = dict(state, W_enc=state["W_enc"].T, W_dec=state["W_dec"].T)
    src = tmp_path / "params.npz"
    np.savez(src, **flipped)
    out = tmp_path / "out.npz"
    cgs.convert(str(src), str(out))
    sae = sae_ops.load(str(out))
    np.testing.assert_allclose(np.asarray(sae.w_enc), state["W_enc"])
    np.testing.assert_allclose(np.asarray(sae.w_dec), state["W_dec"])


def test_convert_torch_state_dict_with_log_threshold(tmp_path):
    torch = pytest.importorskip("torch")
    state = _state(np.random.default_rng(4))
    sd = {
        "W_enc": torch.tensor(state["W_enc"]),
        "b_enc": torch.tensor(state["b_enc"]),
        "W_dec": torch.tensor(state["W_dec"]),
        "b_dec": torch.tensor(state["b_dec"]),
        "log_threshold": torch.tensor(np.log(state["threshold"])),
    }
    src = tmp_path / "sae.pt"
    torch.save(sd, str(src))
    out = tmp_path / "out.npz"
    cgs.convert(str(src), str(out))
    sae = sae_ops.load(str(out))
    np.testing.assert_allclose(np.asarray(sae.threshold), state["threshold"],
                               rtol=1e-6)


def test_convert_rejects_missing_keys(tmp_path):
    src = tmp_path / "params.npz"
    np.savez(src, W_enc=np.zeros((D, S), np.float32))
    assert cgs.main([str(src), str(tmp_path / "out.npz")]) == 1


def _snapshot(tmp_path, layers=(1, 2), leaf="average_l0_10"):
    """Synthetic gemma-scope snapshot: layer_<L>/width_32/<leaf>/params.npz."""
    states = {}
    for i, layer in enumerate(layers):
        state = _state(np.random.default_rng(10 + i))
        d = tmp_path / f"layer_{layer}" / "width_32" / leaf
        d.mkdir(parents=True)
        np.savez(d / "params.npz", **state)
        states[layer] = state
    return states


def test_parse_cells():
    assert cgs.parse_cells("20:16384, 31:16384:layer_31/width_16k/x") == [
        (20, 16384, None), (31, 16384, "layer_31/width_16k/x")]
    with pytest.raises(ValueError):
        cgs.parse_cells("20")
    with pytest.raises(ValueError):
        cgs.parse_cells("a:b")
    with pytest.raises(ValueError):
        cgs.parse_cells(",")


def test_convert_cells_writes_versioned_artifacts(tmp_path):
    from taboo_brittleness_tpu.grid import spec as grid_spec

    states = _snapshot(tmp_path)
    out_dir = tmp_path / "cells"
    assert cgs.main([str(tmp_path), str(out_dir), "--cells", "1:32,2:32"]) == 0

    spec = grid_spec.GridSpec.build([1, 2], [32], artifact_dir=str(out_dir))
    for cell in spec.cells:
        assert os.path.basename(cell.path) == f"{cell.key}.npz"
        sae = grid_spec.load_cell_sae(cell)  # header validates
        np.testing.assert_allclose(np.asarray(sae.w_enc),
                                   states[cell.layer]["W_enc"])
        with np.load(cell.path) as data:
            assert int(data["__grid_version__"]) == \
                grid_spec.GRID_ARTIFACT_VERSION
            # "canonical" resolved to the single leaf actually present.
            assert str(data["__sae_id__"]) == \
                f"layer_{cell.layer}/width_32/average_l0_10"


def test_convert_cells_header_rejects_mismatched_cell(tmp_path):
    import dataclasses

    from taboo_brittleness_tpu.grid import spec as grid_spec

    _snapshot(tmp_path, layers=(1,))
    out_dir = tmp_path / "cells"
    path = cgs.convert_cell(str(tmp_path), str(out_dir), 1, 32)
    wrong = dataclasses.replace(
        grid_spec.CellSpec(layer=2, width=32), path=path)
    with pytest.raises(ValueError, match="header says layer=1"):
        grid_spec.load_cell_sae(wrong)
    # A plain (headerless) npz is rejected too.
    bare = tmp_path / "bare.npz"
    np.savez(bare, **_state(np.random.default_rng(6)))
    with pytest.raises(ValueError, match="missing header"):
        grid_spec.load_cell_sae(dataclasses.replace(
            grid_spec.CellSpec(layer=1, width=32), path=str(bare)))


def test_convert_cells_rejects_width_mismatch(tmp_path):
    _snapshot(tmp_path, layers=(1,))
    # Source SAE is width 32; asking for a 64-wide cell must fail loudly,
    # not write a mislabeled artifact.
    assert cgs.main([str(tmp_path), str(tmp_path / "cells"),
                     "--cells", "1:64:layer_1/width_32/average_l0_10"]) == 1


def test_convert_cells_canonical_ambiguous(tmp_path):
    _snapshot(tmp_path, layers=(1,), leaf="average_l0_10")
    _snapshot(tmp_path, layers=(1,), leaf="average_l0_99")
    with pytest.raises(FileNotFoundError, match="multiple"):
        cgs.convert_cell(str(tmp_path), str(tmp_path / "cells"), 1, 32)


def test_cli_sae_autoconvert(tmp_path, monkeypatch):
    """cli._sae auto-converts from TABOO_GEMMA_SCOPE_ROOT when no npz given;
    output lands under the working tree (snapshot roots may be read-only)."""
    from taboo_brittleness_tpu import cli
    from taboo_brittleness_tpu.config import Config

    state = _state(np.random.default_rng(5))
    root = tmp_path / "snapshot"
    sae_dir = root / "layer_31" / "width_16k" / "average_l0_76"
    sae_dir.mkdir(parents=True)
    np.savez(sae_dir / "params.npz", **state)
    monkeypatch.setenv("TABOO_GEMMA_SCOPE_ROOT", str(root))
    monkeypatch.chdir(tmp_path)  # converted npz goes to ./results/sae_cache

    sae = cli._sae(Config(), None)
    assert sae.d_model == D and sae.d_sae == S
    assert (tmp_path / "results" / "sae_cache").is_dir()
    # Second call hits the converted cache.
    sae2 = cli._sae(Config(), None)
    np.testing.assert_allclose(np.asarray(sae2.w_enc), state["W_enc"])

    monkeypatch.delenv("TABOO_GEMMA_SCOPE_ROOT")
    with pytest.raises(SystemExit):
        cli._sae(Config(), None)
