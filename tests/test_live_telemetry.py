"""Live telemetry (ISSUE 15): windowed metrics spool, SLO burn engine,
crash flight recorder, ``tbx top``, and the ``trace_report --check``
stream invariants.

Layers:

- ``obs.timeseries``: window/exit record schema, counter conservation
  (``total_i == total_{i-1} + delta_i``), seq resume across incarnations,
  torn-tail tolerance, and the ``obs.metrics_write`` fault site (a failed
  spool write drops the window — counted and CONFESSED in the stream,
  never fatal);
- ``obs.slo``: ratio/histogram/gauge burn math, multi-window fast+slow
  spans, burn decay as good windows age badness out, and one-alert-per-
  episode latching;
- ``obs.flightrec``: bounded ring + atomic dump, the serve-quarantine
  trigger (the poisoned step is IN the frozen ring), and the SIGTERM
  drain trigger (a subprocess killed the way the supervisor kills wedges);
- ``tools/trace_report --check``: the new spool checkers accept the real
  recorder's output and reject seeded corruption (broken conservation,
  non-monotone seq, exit/window drift);
- ``obs.top``: collect/render over the committed fleet fixture and over a
  seeded latency regression (nonzero ``slo.burn`` must show);
- satellite 1 regression: a latency step-change moves the WINDOWED p99
  within two window rolls while the cumulative p99 stays put — the
  arithmetic masking the windowed view exists to defeat;
- satellite 6: the jit entry-point registry and the committed tbx-check
  baseline must not grow as a side effect of telemetry work.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from taboo_brittleness_tpu.obs import flightrec
from taboo_brittleness_tpu.obs import metrics as obs_metrics
from taboo_brittleness_tpu.obs import slo as obs_slo
from taboo_brittleness_tpu.obs import timeseries, top
from taboo_brittleness_tpu.obs.progress import ProgressReporter
from taboo_brittleness_tpu.runtime import resilience, supervise
from taboo_brittleness_tpu.runtime.resilience import FaultInjector

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
FLEET_FIXTURE = os.path.join(_REPO, "tests", "fixtures", "obs", "fleet")

if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)
import trace_report  # noqa: E402  (tools/ is not a package)


@pytest.fixture(autouse=True)
def _clean_state():
    obs_metrics.reset()
    flightrec.reset()
    resilience.set_injector(FaultInjector())
    yield
    obs_metrics.reset()
    flightrec.reset()
    resilience.set_injector(FaultInjector())


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _read_spool(path):
    return list(timeseries.iter_windows(path))


# ---------------------------------------------------------------------------
# Windowed spool: schema, conservation, resume, torn tails, fault site.
# ---------------------------------------------------------------------------

def test_window_and_exit_records_conserve(tmp_path):
    """The recorder's own output must satisfy every invariant the checker
    holds streams to: monotone seq/t0, exact counter conservation, and an
    exit record identical to the final window's snapshot."""
    reg = obs_metrics.MetricsRegistry()
    clock = FakeClock()
    path = str(tmp_path / "_metrics.jsonl")
    rec = timeseries.TimeseriesRecorder(path, registry=reg, window_s=10.0,
                                        sample_memory=False, clock=clock)
    reg.counter("work.units").inc(3)
    reg.gauge("work.depth").set(2.0)
    for v in (0.1, 0.2, 0.3):
        reg.histogram("work.latency").observe(v)
    clock.advance(10.0)
    rec.roll()
    reg.counter("work.units").inc(4)
    clock.advance(10.0)
    rec.roll()
    clock.advance(2.0)
    rec.stop()                                  # final roll + exit record

    records = _read_spool(path)
    kinds = [r["kind"] for r in records]
    assert kinds == ["window", "window", "window", "exit"]
    w1, w2, w3, ex = records
    for r in records:
        assert r["v"] == timeseries.SCHEMA_VERSION
        assert r["pid"] == os.getpid()
    assert [r["seq"] for r in records] == [1, 2, 3, 4]
    assert w1["t1"] == pytest.approx(10.0) and w2["t0"] == pytest.approx(10.0)
    assert w1["counters"]["work.units"] == {"total": 3.0, "delta": 3.0}
    assert w2["counters"]["work.units"] == {"total": 7.0, "delta": 4.0}
    assert w3["counters"]["work.units"] == {"total": 7.0, "delta": 0.0}
    assert w1["gauges"]["work.depth"] == 2.0
    h = w1["histograms"]["work.latency"]
    assert h["n"] == 3 and h["cum_n"] == 3
    assert h["p50"] <= h["p99"] <= h["max"] == pytest.approx(0.3)
    # Window 2 saw no new samples: the fork reset, cumulative kept.
    assert w2["histograms"]["work.latency"]["n"] == 0
    assert w2["histograms"]["work.latency"]["cum_n"] == 3
    # Exit ≡ final window (exact, by construction).
    assert ex["counters"]["work.units"] == w3["counters"]["work.units"]["total"]
    assert ex["histograms"]["work.latency"]["cum_n"] == 3
    assert ex["t"] == w3["t1"]
    assert trace_report._check_metrics_file(path) == []


def test_seq_resumes_and_torn_tail_is_skipped(tmp_path):
    """A relaunched incarnation appends a strictly-monotone stream even when
    the previous incarnation died mid-write (torn final line)."""
    reg = obs_metrics.MetricsRegistry()
    clock = FakeClock()
    path = str(tmp_path / "_metrics.jsonl")
    rec = timeseries.TimeseriesRecorder(path, registry=reg, window_s=1.0,
                                        sample_memory=False, clock=clock)
    clock.advance(1.0)
    rec.roll()
    clock.advance(1.0)
    rec.roll()
    rec.stop()
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "window", "seq": 9999, "tr')  # torn tail

    # 2 rolls + stop's final roll + exit = seqs 1..4; the tear adds nothing.
    assert timeseries._resume_seq(path) == 4
    good = _read_spool(path)                    # non-strict: skips the tear
    assert [r["seq"] for r in good] == [1, 2, 3, 4]
    with pytest.raises(ValueError):
        list(timeseries.iter_windows(path, strict=True))

    rec2 = timeseries.TimeseriesRecorder(path, registry=reg, window_s=1.0,
                                         sample_memory=False, clock=clock)
    clock.advance(1.0)
    rec2.roll()
    rec2.stop()
    seqs = [r["seq"] for r in _read_spool(path)]
    assert seqs == sorted(seqs) and seqs[-1] > 4


def test_metrics_write_fault_drops_window_and_confesses(tmp_path, monkeypatch):
    """The deliberate ``obs.metrics_write`` fault site: an injected sink
    fault costs one window (drop-counted), the run survives, and the NEXT
    window confesses the gap via ``obs.metrics_dropped`` — which is exactly
    what lets the conservation checker accept the stream."""
    monkeypatch.setenv("TABOO_FAULT_PLAN", json.dumps(
        {"obs.metrics_write": {"mode": "fail", "kind": "permanent",
                               "times": 1}}))
    resilience.set_injector(None)               # rebuild from env
    clock = FakeClock()
    path = str(tmp_path / "_metrics.jsonl")
    # Global registry on purpose: the drop counter lands there.
    rec = timeseries.TimeseriesRecorder(path, window_s=5.0,
                                        sample_memory=False, clock=clock)
    obs_metrics.counter("work.units").inc(2)
    clock.advance(5.0)
    assert rec.roll() is not None               # rolled, but the write died
    assert rec.dropped == 1
    assert obs_metrics.counter("obs.metrics_dropped").value == 1.0
    assert _read_spool(path) == []
    obs_metrics.counter("work.units").inc(5)
    clock.advance(5.0)
    rec.roll()
    rec.stop()

    records = _read_spool(path)
    assert [r["kind"] for r in records] == ["window", "window", "exit"]
    first = records[0]
    # The surviving stream states totals the dropped window never reported
    # (total 7 with delta 5) AND carries the confession.
    assert first["counters"]["work.units"]["total"] == 7.0
    assert first["counters"]["work.units"]["delta"] == 5.0
    assert first["counters"]["obs.metrics_dropped"]["total"] == 1.0
    assert trace_report._check_metrics_file(path) == []


def test_checker_rejects_seeded_corruption(tmp_path):
    """Negative control for --check: conservation breaks, seq regressions,
    and exit/window drift must each be flagged."""
    reg = obs_metrics.MetricsRegistry()
    clock = FakeClock()
    clean = str(tmp_path / "_metrics.jsonl")
    rec = timeseries.TimeseriesRecorder(clean, registry=reg, window_s=1.0,
                                        sample_memory=False, clock=clock)
    reg.counter("c").inc(2)
    clock.advance(1.0)
    rec.roll()
    reg.counter("c").inc(1)
    clock.advance(1.0)
    rec.roll()
    rec.stop()
    records = _read_spool(clean)
    assert trace_report._check_metrics_file(clean) == []

    def _variant(name, mutate):
        out = str(tmp_path / name)
        lines = [dict(r) for r in records]
        mutate(lines)
        with open(out, "w") as f:
            for r in lines:
                f.write(json.dumps(r) + "\n")
        return trace_report._check_metrics_file(out)

    def _break_total(lines):
        lines[1]["counters"]["c"]["total"] = 99.0

    def _break_seq(lines):
        lines[1]["seq"] = lines[0]["seq"]

    def _break_exit(lines):
        lines[-1]["counters"]["c"] = 123.0

    errs = _variant("bad_total.jsonl", _break_total)
    assert any("conservation" in e for e in errs)
    errs = _variant("bad_seq.jsonl", _break_seq)
    assert any("not increasing" in e for e in errs)
    errs = _variant("bad_exit.jsonl", _break_exit)
    assert any("exit" in e and "conservation" in e for e in errs)


def test_merge_metrics_stamps_workers_and_renumbers(tmp_path):
    """Fleet merge: per-worker spools concatenate into one checker-clean
    stream — seq renumbered globally, every record worker-stamped, the
    per-worker epochs intact."""
    from taboo_brittleness_tpu.runtime import fleet

    for wid, n in (("w0", 2), ("w1", 3)):
        reg = obs_metrics.MetricsRegistry()
        clock = FakeClock()
        rec = timeseries.TimeseriesRecorder(
            str(tmp_path / timeseries.metrics_filename(wid)),
            registry=reg, window_s=1.0, sample_memory=False, clock=clock)
        for _ in range(n):
            reg.counter("c").inc()
            clock.advance(1.0)
            rec.roll()
        rec.stop()

    merged = fleet.merge_metrics(str(tmp_path), ["w0", "w1"])
    # Per worker: n rolls + stop's final roll + one exit record.
    assert merged == (2 + 2) + (3 + 2)
    path = str(tmp_path / timeseries.METRICS_FILENAME)
    records = _read_spool(path)
    assert len(records) == merged
    assert [r["seq"] for r in records] == list(range(1, merged + 1))
    assert {r["worker"] for r in records} == {"w0", "w1"}
    assert trace_report._check_metrics_file(path) == []


# ---------------------------------------------------------------------------
# Satellite 1: the step-change regression the windowed view exists for.
# ---------------------------------------------------------------------------

def test_latency_step_change_moves_windowed_p99_within_two_windows():
    """Seed a latency step-change: the windowed p99 reaches the regressed
    value by the second window roll, while the cumulative p99 (the number
    the heartbeat used to sell as "rolling") does not move at all."""
    from taboo_brittleness_tpu.serve.scheduler import SlotScheduler

    h = obs_metrics.histogram("serve.latency.chat")
    for _ in range(512):
        h.observe(0.08)                         # healthy steady state
    h.roll_window()                             # window 1 closes
    for _ in range(4):
        h.observe(5.0)                          # the regression lands
    h.roll_window()                             # window 2 closes

    # Through the REAL serve surface (latency_percentiles reads the
    # registry + the completed-scenario set; no engine needed).
    sched = SlotScheduler.__new__(SlotScheduler)
    sched._scenarios_completed = {"chat"}
    pct = sched.latency_percentiles()
    cell = pct["scenarios"]["chat"]
    assert cell["window"]["p99_s"] == pytest.approx(5.0)
    assert cell["window"]["n"] == 4
    # 4 slow samples out of 516 sit far above the cumulative p99 rank: the
    # since-start reservoir arithmetically masks the regression.
    assert cell["cumulative"]["p99_s"] == pytest.approx(0.08)
    assert cell["cumulative"]["n"] == 516


def test_heartbeat_carries_latency_window_and_slo_block(tmp_path):
    """The heartbeat contract: ``serving.latency`` keeps its window stamp
    and the top-level ``slo`` block rides both serving updates and
    ``set_slo`` (sweep mode)."""
    rep = ProgressReporter(str(tmp_path / "_progress.json"), total_words=0,
                           interval=3600)
    block = {"serve_goodput": {"burn": 3.5, "fast": 3.5, "slow": 4.0,
                               "ok": False}}
    rep.serving_update(in_flight=1, completed=2,
                       latency={"window_s": 10.0, "scenarios": {}},
                       slo=block)
    snap = rep.snapshot()
    assert snap["serving"]["latency"]["window_s"] == 10.0
    assert snap["slo"]["serve_goodput"]["burn"] == 3.5
    rep.set_slo({"serve_goodput": {"burn": 0.0, "fast": 0.0, "slow": 0.0,
                                   "ok": True}})
    assert rep.snapshot()["slo"]["serve_goodput"]["ok"] is True


# ---------------------------------------------------------------------------
# SLO burn engine.
# ---------------------------------------------------------------------------

def _goodput_target(**over):
    kw = dict(name="serve_goodput", source="ratio", metric="serve.completed",
              metric_b="serve.admitted", threshold=0.99, op="ge",
              budget=0.01, fast_windows=1, slow_windows=6)
    kw.update(over)
    return obs_slo.SloTarget(**kw)


def test_ratio_burn_rises_then_decays():
    reg = obs_metrics.MetricsRegistry()
    eng = obs_slo.SloEngine([_goodput_target()], registry=reg,
                            emit_alerts=False)
    block = eng.observe_window(
        dur=10.0, hists={}, gauges={},
        counter_deltas={"serve.admitted": 100.0, "serve.completed": 90.0})
    cell = block["serve_goodput"]
    # One bad window over a 1% budget burns 100x on both spans.
    assert cell["fast"] == pytest.approx(100.0)
    assert cell["burn"] == pytest.approx(100.0)
    assert not cell["ok"]
    assert reg.gauge("slo.burn.serve_goodput").value == pytest.approx(100.0)
    # Good windows age the badness out: fast clears immediately, the burn
    # gauge (min of spans) with it; after slow_windows the slow span is
    # clean too.
    for i in range(6):
        block = eng.observe_window(
            dur=10.0, hists={}, gauges={},
            counter_deltas={"serve.admitted": 50.0, "serve.completed": 50.0})
        assert block["serve_goodput"]["fast"] == 0.0
        assert block["serve_goodput"]["burn"] == 0.0
    assert block["serve_goodput"]["slow"] == 0.0
    assert block["serve_goodput"]["ok"]


def test_histogram_target_counts_per_sample_violations():
    reg = obs_metrics.MetricsRegistry()
    target = obs_slo.SloTarget(name="serve_latency", source="histogram",
                               metric="serve.latency.*", threshold=1.0,
                               op="le", budget=0.05)
    eng = obs_slo.SloEngine([target], registry=reg, emit_alerts=False)
    win = {"n": 10, "sum": 8.0, "min": 0.5, "max": 2.0,
           "samples": [0.5] * 8 + [2.0] * 2, "cum_n": 10}
    block = eng.observe_window(dur=10.0, hists={"serve.latency.chat": win},
                               counter_deltas={}, gauges={})
    # 2/10 samples over threshold against a 5% budget -> 4x burn, fanned
    # out per scenario (the wildcard tail names the series).
    assert block["serve_latency.chat"]["burn"] == pytest.approx(4.0)
    assert reg.gauge("slo.burn.serve_latency.chat").value == pytest.approx(4.0)


def test_gauge_target_and_idle_windows():
    reg = obs_metrics.MetricsRegistry()
    target = obs_slo.SloTarget(name="hbm_headroom", source="gauge",
                               metric="mem.hbm.headroom_frac",
                               threshold=0.05, op="ge", budget=0.01,
                               slow_windows=3)
    eng = obs_slo.SloEngine([target], registry=reg, emit_alerts=False)
    block = eng.observe_window(dur=10.0, hists={}, counter_deltas={},
                               gauges={"mem.hbm.headroom_frac": 0.01})
    assert block["hbm_headroom"]["burn"] == pytest.approx(100.0)
    # Idle windows (gauge gone) still advance the KNOWN series with (0, 0)
    # so the episode ages out instead of latching forever.
    for _ in range(3):
        block = eng.observe_window(dur=10.0, hists={}, counter_deltas={},
                                   gauges={})
    assert block["hbm_headroom"]["burn"] == 0.0
    assert block["hbm_headroom"]["ok"]


def test_alert_latches_once_per_episode(monkeypatch):
    import taboo_brittleness_tpu.obs as obs_pkg

    calls = []
    monkeypatch.setattr(obs_pkg, "warn",
                        lambda msg, **kw: calls.append((msg, kw)))
    reg = obs_metrics.MetricsRegistry()
    eng = obs_slo.SloEngine([_goodput_target(slow_windows=1)], registry=reg)
    bad = {"serve.admitted": 10.0, "serve.completed": 5.0}
    good = {"serve.admitted": 10.0, "serve.completed": 10.0}
    for _ in range(3):
        eng.observe_window(dur=10.0, hists={}, gauges={}, counter_deltas=bad)
    assert len(calls) == 1                      # sustained episode: one alert
    assert calls[0][1]["name"] == "slo.alert"
    eng.observe_window(dur=10.0, hists={}, gauges={}, counter_deltas=good)
    eng.observe_window(dur=10.0, hists={}, gauges={}, counter_deltas=bad)
    assert len(calls) == 2                      # recovery re-arms the latch


def test_load_targets_from_env(monkeypatch, tmp_path):
    spec = [{"name": "x", "source": "gauge", "metric": "g",
             "threshold": 1.0, "op": "ge"}]
    monkeypatch.setenv("TBX_SLO", json.dumps(spec))
    targets = obs_slo.default_targets()
    assert [t.name for t in targets] == ["x"]
    p = tmp_path / "slo.json"
    p.write_text(json.dumps(spec))
    monkeypatch.setenv("TBX_SLO", str(p))
    assert [t.name for t in obs_slo.default_targets()] == ["x"]
    with pytest.raises((ValueError, TypeError)):
        obs_slo.load_targets(json.dumps([{"name": "bad", "source": "nope",
                                          "metric": "m", "threshold": 1.0}]))


def test_recorder_feeds_engine_and_spools_burn(tmp_path):
    """End-to-end across timeseries+slo: a seeded latency regression rolls
    into a window record carrying a nonzero burn block, and the burn gauge
    itself rides the NEXT window (the spool sees its own alarm)."""
    reg = obs_metrics.MetricsRegistry()
    target = obs_slo.SloTarget(name="serve_latency", source="histogram",
                               metric="serve.latency.*", threshold=0.5,
                               op="le", budget=0.05)
    eng = obs_slo.SloEngine([target], registry=reg, emit_alerts=False)
    clock = FakeClock()
    seen = []
    rec = timeseries.TimeseriesRecorder(
        str(tmp_path / "_metrics.jsonl"), registry=reg, window_s=1.0,
        slo_engine=eng, on_window=seen.append, sample_memory=False,
        clock=clock)
    for _ in range(10):
        reg.histogram("serve.latency.chat").observe(5.0)   # all bad
    clock.advance(1.0)
    rec.roll()
    clock.advance(1.0)
    rec.roll()
    rec.stop()

    assert seen[0]["slo"]["serve_latency.chat"]["burn"] == pytest.approx(20.0)
    assert not seen[0]["slo"]["serve_latency.chat"]["ok"]
    assert rec.last_slo() is not None
    gauges = seen[1]["gauges"]
    assert gauges["slo.burn.serve_latency.chat"] == pytest.approx(20.0)
    assert trace_report._check_metrics_file(
        str(tmp_path / "_metrics.jsonl")) == []


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------

def test_flightrec_ring_bounds_and_atomic_dump(tmp_path):
    fr = flightrec.FlightRecorder(capacity=4)
    assert fr.dump("early") is None             # unconfigured: no-op
    fr.configure(str(tmp_path))
    for i in range(7):
        fr.record("step", i=i)
    path = fr.dump("test", word="ship")
    assert path == str(tmp_path / "_flightrec.json")
    with open(path) as f:
        data = json.load(f)
    assert data["v"] == flightrec.SCHEMA_VERSION
    assert data["reason"] == "test" and data["capacity"] == 4
    assert [r["i"] for r in data["ring"]] == [3, 4, 5, 6]   # bounded: last 4
    assert all("t" in r and r["kind"] == "step" for r in data["ring"])
    assert data["context"] == {"word": "ship"}
    assert trace_report.check_flightrec(
        str(tmp_path / "_events.jsonl")) == []
    # capacity=0 disables recording wholesale.
    off = flightrec.FlightRecorder(capacity=0)
    off.configure(str(tmp_path))
    off.record("step")
    assert off.snapshot() == [] and off.dump("test") is None


def test_quarantine_dump_freezes_the_ring(tmp_path):
    """The resilience quarantine path (the trigger the fleet fixture uses):
    run_guarded's final failure dumps the ring with the word's attempt and
    quarantine records in it."""
    flightrec.configure(str(tmp_path))
    flightrec.record("word.step", word="ship", step=7)

    def _boom():
        raise TimeoutError("injected")          # transient: retried first

    out = resilience.run_guarded(
        "ship", _boom,
        policy=resilience.RetryPolicy(max_retries=1, base_delay=0.0,
                                      jitter=0.0))
    assert not out.ok
    with open(tmp_path / "_flightrec.json") as f:
        data = json.load(f)
    assert data["reason"] == "quarantine"
    kinds = [r["kind"] for r in data["ring"]]
    assert kinds[0] == "word.step"
    assert "word.attempt" in kinds and "word.retry" in kinds
    assert kinds[-1] == "word.quarantine"
    assert data["ring"][-1]["word"] == "ship"


def test_sigterm_drain_dumps_flightrec(tmp_path):
    """The signal trigger, end to end in a real subprocess: SIGTERM (what
    the supervisor sends before any wedge-kill escalates to SIGKILL) lands
    in DrainController._handle, which freezes the ring from signal context
    without touching any lock."""
    child = (
        "import os, sys, time\n"
        "from taboo_brittleness_tpu.obs import flightrec\n"
        "from taboo_brittleness_tpu.runtime import supervise\n"
        "flightrec.configure(sys.argv[1])\n"
        "flightrec.record('serve.step', in_flight=2, requests=['a', 'b'])\n"
        "supervise.install_drain_handlers()\n"
        "print('ready', flush=True)\n"
        "t0 = time.monotonic()\n"
        "while not supervise.drain_requested():\n"
        "    if time.monotonic() - t0 > 30: sys.exit(3)\n"
        "    time.sleep(0.05)\n"
        "sys.exit(0)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", child, str(tmp_path)],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env)
    try:
        assert proc.stdout.readline().strip() == "ready"
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        proc.kill()
    with open(tmp_path / "_flightrec.json") as f:
        data = json.load(f)
    assert data["reason"] == f"signal:{signal.SIGTERM}"
    assert data["ring"][0]["kind"] == "serve.step"
    assert data["ring"][0]["requests"] == ["a", "b"]


# ---------------------------------------------------------------------------
# tbx top.
# ---------------------------------------------------------------------------

def test_top_renders_committed_fleet_fixture(capsys):
    """The committed chaos fixture (3 workers, one killed, one quarantine
    dump) must collect and render: worker lanes, spool windows, flightrec."""
    state = top.collect(FLEET_FIXTURE)
    lanes = {ln["lane"] for ln in state["lanes"]}
    assert {"main", "w0", "w1", "w2"} <= lanes
    assert state["n_windows"] > 0 and state["latest"] is not None
    assert state["flightrec"] and state["flightrec"][0]["reason"]
    out = top.render(state)
    assert "lanes:" in out and "spool:" in out and "flightrec:" in out
    for wid in ("w0", "w1", "w2"):
        assert wid in out
    assert top.main(["--dir", FLEET_FIXTURE, "--once"]) == 0
    assert top.main_selfcheck(FLEET_FIXTURE) == 0
    capsys.readouterr()


def test_top_shows_seeded_slo_burn(tmp_path):
    """Acceptance (c): a seeded latency regression produces a NONZERO
    slo.burn in the rendered frame, flagged as alerting."""
    reg = obs_metrics.MetricsRegistry()
    target = obs_slo.SloTarget(name="serve_latency", source="histogram",
                               metric="serve.latency.*", threshold=0.5,
                               op="le", budget=0.05)
    eng = obs_slo.SloEngine([target], registry=reg, emit_alerts=False)
    clock = FakeClock()
    rec = timeseries.TimeseriesRecorder(
        str(tmp_path / "_metrics.jsonl"), registry=reg, window_s=1.0,
        slo_engine=eng, sample_memory=False, clock=clock)
    for _ in range(10):
        reg.histogram("serve.latency.chat").observe(5.0)
    clock.advance(1.0)
    rec.roll()
    # Keep the regression hot through stop()'s final roll so the LATEST
    # window (the one top renders) still burns.
    for _ in range(10):
        reg.histogram("serve.latency.chat").observe(5.0)
    clock.advance(1.0)
    rec.stop()
    rep = ProgressReporter(str(tmp_path / "_progress.json"), total_words=0,
                           interval=3600)
    rep.serving_update(in_flight=1, completed=9)
    rep.write_now()

    state = top.collect(str(tmp_path))
    assert state["latest"]["slo"]["serve_latency.chat"]["burn"] > 0
    out = top.render(state)
    assert "serve_latency.chat" in out
    assert "ALERT" in out


def test_top_tolerates_torn_spool_tail(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    clock = FakeClock()
    rec = timeseries.TimeseriesRecorder(
        str(tmp_path / "_metrics.jsonl"), registry=reg, window_s=1.0,
        sample_memory=False, clock=clock)
    reg.counter("c").inc()
    clock.advance(1.0)
    rec.roll()
    rec.stop()
    with open(tmp_path / "_metrics.jsonl", "a") as f:
        f.write('{"kind": "window", "seq": 99, "tor')
    state = top.collect(str(tmp_path))
    # roll + stop's final roll = 2 windows; the tear is skipped, not fatal.
    assert state["n_windows"] == 2
    assert top.render(state)


# ---------------------------------------------------------------------------
# Satellite 6: telemetry must not grow the jit surface or the baseline.
# ---------------------------------------------------------------------------

def test_entry_points_and_baseline_unchanged():
    from taboo_brittleness_tpu.analysis import deep

    assert sorted(name for name, _ in deep.ENTRY_POINTS) == [
        "grid.runner._cell_readout",
        "ops.lens.aggregate_from_residual",
        "ops.sae.latent_secret_correlation_stream",
        "pipelines.interventions._nll_cached_jit",
        "pipelines.interventions._residual_measure",
        "runtime.decode.greedy_decode",
        "runtime.decode.greedy_decode[multi_tap]",
        "runtime.delta.apply_delta",
        "runtime.fused.fused_study",
        "runtime.speculate.draft_step",
        "runtime.speculate.verify_block",
        "serve.engine.serve_step",
        "serve.engine.serve_step[tp]",
        "serve.engine.serve_step_multi",
        "serve.engine.serve_step_multi[tp]",
        "serve.spec_engine.serve_spec_draft",
        "serve.spec_engine.serve_spec_draft[tp]",
        "serve.spec_engine.serve_spec_verify",
        "serve.spec_engine.serve_spec_verify[tp]",
    ]
    with open(os.path.join(_REPO, "tools", "tbx_baseline.json")) as f:
        baseline = json.load(f)
    assert baseline["version"] == 2    # move-stable fingerprints (scope-keyed)
    # 13 pre-tp + the 4 [tp] local-shard readout transients traced on the
    # forced 8-device mesh + their 3 distinct 1-device-fallback shapes
    # (verify's coincides), so the gate is green at either device count.
    assert len(baseline["findings"]) == 20
