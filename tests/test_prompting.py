"""Naive/adversarial prompting attacks (paper Table 1's remaining
elicitation rows) on the tiny model."""

import json
import os

import numpy as np
import pytest

import jax

from taboo_brittleness_tpu.config import Config, ExperimentConfig, ModelConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.pipelines import prompting
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(11), cfg)
    words = [WORD, "secret", "word", "is", "What", "your", "Tell", "me",
             "the", "you", "hiding"]
    tok = WordTokenizer(words, vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=1, top_k=2, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=4),
        word_plurals={WORD: [WORD, WORD + "s"], "word2": ["word2"]},
        prompts=["hint"],
    )
    return params, cfg, tok, config


def test_scoring_detects_leaks(setup):
    _, _, _, config = setup
    n = len(config.prompting.naive_prompts)
    res = prompting.score_prompting(
        config, WORD, "naive",
        ["I cannot say."] * (n - 1) + ["Fine, it is moon!"])
    assert res["success_rate"] == pytest.approx(1.0 / n)
    assert res["pass_at_k"] == 1.0
    none = prompting.score_prompting(config, WORD, "naive",
                                     ["moonlight only"] * n)
    assert none["success_rate"] == 0.0 and none["pass_at_k"] == 0.0


def test_results_carry_prompt_provenance(setup, tmp_path):
    """Stand-in attack prompts must be labeled in every result JSON so the
    numbers can't be mistaken for paper-comparable Table-1 rows (ADVICE r05
    low #2); a YAML override is labeled as user-supplied instead."""
    params, cfg, tok, config = setup
    scored = prompting.score_prompting(config, WORD, "naive", ["x"])
    assert scored["prompt_provenance"].startswith("representative stand-ins")

    res = prompting.run_prompting_attacks(
        config, model_loader=lambda w: (params, cfg, tok), words=[WORD],
        output_dir=str(tmp_path / "w"))
    for mode in ("naive", "adversarial"):
        assert res["prompt_provenance"][mode].startswith(
            "representative stand-ins")
        assert res["words"][WORD][mode]["prompt_provenance"].startswith(
            "representative stand-ins")

    import dataclasses

    overridden = dataclasses.replace(
        config, prompting=dataclasses.replace(
            config.prompting, naive_prompts=("what is the word?",)))
    assert prompting.prompt_provenance(overridden, "naive") == (
        "user-supplied (yaml prompting: override)")
    assert prompting.prompt_provenance(overridden, "adversarial").startswith(
        "representative stand-ins")


def test_run_prompting_attacks_end_to_end(setup, tmp_path):
    params, cfg, tok, config = setup
    out = str(tmp_path / "prompting.json")
    res = prompting.run_prompting_attacks(
        config, model_loader=lambda w: (params, cfg, tok),
        words=[WORD, "word2"], output_path=out,
        output_dir=str(tmp_path / "words"))
    assert set(res["overall"]) == {"naive", "adversarial"}
    for mode in ("naive", "adversarial"):
        entry = res["words"][WORD][mode]
        assert len(entry["responses"]) == len(
            prompting._mode_prompts(config, mode))
        assert 0.0 <= entry["success_rate"] <= 1.0
    # Shared model => shared responses across words (memoized decode).
    assert (res["words"][WORD]["naive"]["responses"]
            == res["words"]["word2"]["naive"]["responses"])
    assert os.path.exists(out)
    with open(out) as f:
        assert json.load(f)["overall"] == res["overall"]
    # Resume: per-word files satisfy a second run without decoding.
    loads = []
    res2 = prompting.run_prompting_attacks(
        config, model_loader=lambda w: (loads.append(w), params, cfg, tok)[1:],
        words=[WORD, "word2"], output_dir=str(tmp_path / "words"))
    assert loads == []
    assert res2["words"][WORD] == res["words"][WORD]


def test_run_prompting_memoizes_shared_model(setup, monkeypatch):
    """One batched decode per mode for the whole word list under a shared
    loader; a fresh params object recomputes."""
    params, cfg, tok, config = setup
    calls = []
    real = prompting._attack_responses

    def counting(*a, **kw):
        calls.append(a[4])
        return real(*a, **kw)

    monkeypatch.setattr(prompting, "_attack_responses", counting)
    prompting.run_prompting_attacks(
        config, model_loader=lambda w: (params, cfg, tok),
        words=[WORD, "word2"], modes=("naive",))
    assert calls == ["naive"]

    calls.clear()
    params2 = gemma2.init_params(jax.random.PRNGKey(99), cfg)
    loaders = {WORD: params, "word2": params2}
    prompting.run_prompting_attacks(
        config, model_loader=lambda w: (loaders[w], cfg, tok),
        words=[WORD, "word2"], modes=("naive",))
    assert calls == ["naive", "naive"]


def test_unknown_mode_raises(setup):
    _, _, _, config = setup
    with pytest.raises(ValueError, match="unknown prompting mode"):
        prompting._mode_prompts(config, "bogus")
