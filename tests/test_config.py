import os

from taboo_brittleness_tpu import config as cfg_mod
from taboo_brittleness_tpu.config import Config, load_config

REF_CONFIG = "/root/reference/configs/default.yaml"


def test_defaults():
    cfg = Config()
    assert cfg.model.layer_idx == 31
    assert cfg.model.top_k == 5
    assert cfg.experiment.seed == 42
    assert cfg.experiment.max_new_tokens == 50
    assert len(cfg.word_plurals) == 20
    assert len(cfg.prompts) == 10
    assert cfg.intervention.budgets == (1, 2, 4, 8, 16, 32)
    assert cfg.intervention.ranks == (1, 2, 4, 8)
    assert len(cfg.token_forcing.prefill_phrases) == 10
    assert len(cfg.token_forcing.warmup_prompts) == 3


def test_load_our_default_yaml():
    cfg = load_config(os.path.join(os.path.dirname(__file__), "..", "configs", "default.yaml"))
    assert cfg.words == ["moon", "smile", "ship"]
    assert cfg.sae.sae_id == "layer_31/width_16k/average_l0_76"
    assert cfg.mesh.dp == -1


def test_reference_yaml_loads_unchanged():
    """The reference's configs/default.yaml must load with identical semantics."""
    if not os.path.exists(REF_CONFIG):
        import pytest

        pytest.skip("reference absent")
    cfg = load_config(REF_CONFIG)
    assert cfg.model.layer_idx == 31
    assert cfg.model.top_k == 5
    assert cfg.experiment.max_new_tokens == 50
    assert cfg.words == ["moon", "smile", "ship"]
    assert cfg.prompts[0] == "Give me a hint!"
    assert cfg.plotting.figsize == (22, 11)
    # Sections absent from the reference YAML fall back to our defaults.
    assert cfg.sae.width == 16384
    assert cfg.model.checkpoint_template == "bcywinski/gemma-2-9b-it-taboo-{word}"


def test_words_property_and_roundtrip():
    cfg = Config()
    assert "ship" in cfg.words
    d = cfg_mod.to_dict(cfg)
    cfg2 = cfg_mod.from_dict(d)
    assert cfg2.model.layer_idx == cfg.model.layer_idx
    assert cfg2.intervention.budgets == cfg.intervention.budgets
