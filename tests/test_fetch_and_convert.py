"""End-to-end test of the real-checkpoint on-ramp (tools/fetch_and_convert.py)
on a tiny HF snapshot written to disk — the same safetensors/config.json layout
an actual ``bcywinski/gemma-2-9b-it-taboo-*`` download has (reference
src/models.py:21), so the moment real assets exist the identical code path runs.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.models.params import from_safetensors_dir, from_torch_model
from taboo_brittleness_tpu.runtime import tokenizer as tokenizer_mod
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import fetch_and_convert as fc  # noqa: E402


@pytest.fixture(scope="module")
def tiny_snapshot(tmp_path_factory):
    """A tiny Gemma-2 HF snapshot saved to disk + the torch oracle."""
    from transformers.models.gemma2 import Gemma2Config as HFConfig, Gemma2ForCausalLM

    cfg = gemma2.PRESETS["gemma2_tiny"]
    hf_cfg = HFConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        intermediate_size=cfg.intermediate_size,
        sliding_window=cfg.sliding_window,
        query_pre_attn_scalar=cfg.query_pre_attn_scalar,
        attn_logit_softcapping=cfg.attn_logit_softcap,
        final_logit_softcapping=cfg.final_logit_softcap,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        attn_implementation="eager",
        tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    hf_model = Gemma2ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for name, p in hf_model.named_parameters():
            if "norm" in name:
                p.copy_(0.1 * torch.randn_like(p))

    root = tmp_path_factory.mktemp("ckpt_root")
    snap = root / "gemma-2-9b-it-taboo-moon"
    hf_model.save_pretrained(snap, safe_serialization=True)
    return str(root), str(snap), cfg, hf_model


def test_safetensors_dir_matches_torch_conversion(tiny_snapshot):
    _root, snap, cfg, hf_model = tiny_snapshot
    cfg32 = cfg.replace(dtype="float32", param_dtype="float32")
    from_disk = from_safetensors_dir(snap, cfg32)
    from_torch = from_torch_model(hf_model, cfg32)
    for a, b in zip(*(map(lambda p: __import__("jax").tree_util.tree_leaves(p),
                          (from_disk, from_torch)))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_onramp_skips_cleanly_without_snapshot(tmp_path, capsys):
    rc = fc.main(["--word", "ship", "--checkpoint-root", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SKIPPED" in out


def test_onramp_converts_and_verifies(tiny_snapshot, tmp_path, monkeypatch, capsys):
    root, _snap, cfg, _hf = tiny_snapshot
    monkeypatch.setattr(
        tokenizer_mod.HFTokenizer, "from_pretrained",
        staticmethod(lambda path: WordTokenizer(
            ["moon", "hint", "Give", "me", "a"], vocab_size=cfg.vocab_size)))

    expected = str(tmp_path / "logits_moon.json")
    args = ["--word", "moon", "--checkpoint-root", root,
            "--dtype", "float32", "--param-dtype", "float32",
            "--expected", expected,
            "--reference-processed", str(tmp_path / "no_such_dir")]

    # First run writes the expectation; second run regresses against it.
    assert fc.main(args + ["--write-expected"]) == 0
    assert os.path.exists(expected)
    assert fc.main(args) == 0
    out = capsys.readouterr().out
    assert "FAIL" not in out

    # A corrupted expectation must fail loudly.
    with open(expected) as f:
        exp = json.load(f)
    exp["argmax"] = (exp["argmax"] + 1) % cfg.vocab_size
    with open(expected, "w") as f:
        json.dump(exp, f)
    assert fc.main(args) == 1


def test_onramp_decode_verification_against_cached_sidecars(
        tiny_snapshot, tmp_path, monkeypatch, capsys):
    """--verify-decode replays cached prompts and diffs response_text —
    exercised here against sidecars produced by our own decode (so the check
    passes), then against a corrupted one (so it fails)."""
    root, snap, cfg, _hf = tiny_snapshot
    tok = WordTokenizer(["moon", "hint", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)
    monkeypatch.setattr(tokenizer_mod.HFTokenizer, "from_pretrained",
                        staticmethod(lambda path: tok))

    cfg32 = cfg.replace(dtype="float32", param_dtype="float32")
    params = from_safetensors_dir(snap, cfg32)
    from taboo_brittleness_tpu.runtime import decode

    prompts = ["Give me a hint", "a hint"]
    result, _texts, prompt_ids = decode.generate(
        params, cfg32, tok, prompts, max_new_tokens=4)
    processed = tmp_path / "processed" / "moon"
    processed.mkdir(parents=True)
    for i, p in enumerate(prompts):
        with open(processed / f"prompt_{i + 1:02d}.json", "w") as f:
            json.dump({"prompt": p,
                       "response_text": decode.full_text(
                           tok, prompt_ids[i], result, i)}, f)

    args = ["--word", "moon", "--checkpoint-root", root,
            "--dtype", "float32", "--param-dtype", "float32",
            "--expected", str(tmp_path / "none.json"),
            "--verify-decode", "--max-new-tokens", "4",
            "--reference-processed", str(tmp_path / "processed")]
    assert fc.main(args) == 0
    assert "FAIL" not in capsys.readouterr().out

    side = processed / "prompt_01.json"
    js = json.loads(side.read_text())
    js["response_text"] = js["response_text"] + " CORRUPTED"
    side.write_text(json.dumps(js))
    assert fc.main(args) == 1
