"""Numerics parity: our JAX Gemma-2 vs HF transformers (torch CPU, eager attention).

The torch stack can't run the real 9B here, so a tiny random Gemma2Config is the
oracle (SURVEY.md §4 test plan item 3).  sliding_window=3 < seq exercises the
alternating local/global masking; f32 everywhere so tolerances are tight.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.models.params import from_torch_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny():
    from transformers.models.gemma2 import Gemma2Config as HFConfig, Gemma2ForCausalLM

    cfg = gemma2.PRESETS["gemma2_tiny"]
    hf_cfg = HFConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        intermediate_size=cfg.intermediate_size,
        sliding_window=cfg.sliding_window,
        query_pre_attn_scalar=cfg.query_pre_attn_scalar,
        attn_logit_softcapping=cfg.attn_logit_softcap,
        final_logit_softcapping=cfg.final_logit_softcap,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        attn_implementation="eager",
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf_model = Gemma2ForCausalLM(hf_cfg).eval()
    # Non-trivial norm weights (HF inits them to zeros like ours; randomize to
    # make the (1 + w) convention actually observable).
    with torch.no_grad():
        for name, p in hf_model.named_parameters():
            if "norm" in name:
                p.copy_(0.1 * torch.randn_like(p))
    params = from_torch_model(hf_model, cfg)
    return cfg, hf_model, params


def hf_logits(hf_model, ids: np.ndarray, attention_mask=None) -> np.ndarray:
    with torch.no_grad():
        out = hf_model(
            input_ids=torch.tensor(ids),
            attention_mask=None if attention_mask is None else torch.tensor(attention_mask),
        )
    return out.logits.float().numpy()


def test_forward_logits_match(tiny):
    cfg, hf_model, params = tiny
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12))
    ours = gemma2.forward(params, cfg, jnp.asarray(ids))
    theirs = hf_logits(hf_model, ids)
    np.testing.assert_allclose(np.asarray(ours.logits), theirs, atol=2e-5, rtol=1e-5)


def test_forward_matches_with_left_padding(tiny):
    cfg, hf_model, params = tiny
    rng = np.random.default_rng(2)
    T, pad = 10, 4
    ids = rng.integers(1, cfg.vocab_size, size=(1, T))
    padded = np.concatenate([np.zeros((1, pad), np.int64), ids], axis=1)
    attn = np.concatenate([np.zeros((1, pad), np.int64), np.ones((1, T), np.int64)], axis=1)

    positions = np.concatenate([np.zeros((1, pad), np.int32),
                                np.arange(T, dtype=np.int32)[None, :]], axis=1)
    ours = gemma2.forward(
        params, cfg, jnp.asarray(padded),
        positions=jnp.asarray(positions),
        attn_validity=jnp.asarray(attn, bool),
    )
    theirs = hf_logits(hf_model, ids)  # unpadded oracle
    np.testing.assert_allclose(
        np.asarray(ours.logits[:, pad:]), theirs, atol=6e-5, rtol=1e-5
    )


def test_per_layer_taps_match_hf_hidden_states(tiny):
    cfg, hf_model, params = tiny
    rng = np.random.default_rng(3)
    ids = rng.integers(0, cfg.vocab_size, size=(1, 9))

    ours = gemma2.forward(
        params, cfg, jnp.asarray(ids),
        per_layer_fn=lambda h, idx: h,  # tap raw resid_post at every layer
    )
    with torch.no_grad():
        out = hf_model(input_ids=torch.tensor(ids), output_hidden_states=True)
    # HF hidden_states[0] is the embedding; [i+1] is resid_post of layer i —
    # except the last entry, which HF stores *after* the final norm.
    for layer in range(cfg.num_layers - 1):
        np.testing.assert_allclose(
            np.asarray(ours.taps[layer]),
            out.hidden_states[layer + 1].float().numpy(),
            atol=5e-5, rtol=1e-5,
        )
    last_normed = gemma2.rms_norm(
        ours.taps[cfg.num_layers - 1], params["final_norm"], cfg.rms_norm_eps
    )
    np.testing.assert_allclose(
        np.asarray(last_normed),
        out.hidden_states[-1].float().numpy(),
        atol=5e-5, rtol=1e-5,
    )


def test_kv_cache_prefill_then_decode_matches_full_forward(tiny):
    cfg, hf_model, params = tiny
    rng = np.random.default_rng(4)
    B, T_prompt, T_extra = 2, 7, 5
    ids = rng.integers(0, cfg.vocab_size, size=(B, T_prompt + T_extra))

    full = gemma2.forward(params, cfg, jnp.asarray(ids))

    cache = gemma2.KVCache.zeros(cfg, B, max_len=T_prompt + T_extra)
    pre = gemma2.forward(params, cfg, jnp.asarray(ids[:, :T_prompt]), cache=cache)
    step_logits = [np.asarray(pre.logits[:, -1])]
    cache = pre.cache
    for t in range(T_prompt, T_prompt + T_extra):
        step = gemma2.forward(params, cfg, jnp.asarray(ids[:, t:t + 1]), cache=cache)
        cache = step.cache
        step_logits.append(np.asarray(step.logits[:, 0]))

    # logits at position t from incremental decode == from the full forward
    for offset, lg in enumerate(step_logits):
        np.testing.assert_allclose(
            lg, np.asarray(full.logits[:, T_prompt - 1 + offset]), atol=3e-5, rtol=1e-5
        )


def test_edit_fn_is_applied(tiny):
    cfg, _, params = tiny
    ids = np.arange(8, dtype=np.int64)[None, :] % cfg.vocab_size

    def zero_layer_2(h, idx):
        return jnp.where(idx == 2, jnp.zeros_like(h), h)

    edited = gemma2.forward(params, cfg, jnp.asarray(ids), edit_fn=zero_layer_2,
                            per_layer_fn=lambda h, i: h)
    assert np.abs(np.asarray(edited.taps[2])).max() == 0.0
    assert np.abs(np.asarray(edited.taps[1])).max() > 0.0


def test_greedy_decode_matches_hf_generate(tiny):
    cfg, hf_model, params = tiny
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, size=(1, 6))
    new_tokens = 8

    with torch.no_grad():
        hf_out = hf_model.generate(
            input_ids=torch.tensor(prompt), max_new_tokens=new_tokens,
            do_sample=False, use_cache=True,
        ).numpy()

    cache = gemma2.KVCache.zeros(cfg, 1, max_len=prompt.shape[1] + new_tokens)
    res = gemma2.forward(params, cfg, jnp.asarray(prompt), cache=cache)
    cache = res.cache
    tok = jnp.argmax(res.logits[:, -1], axis=-1)
    generated = [int(tok[0])]
    for _ in range(new_tokens - 1):
        res = gemma2.forward(params, cfg, tok[:, None], cache=cache)
        cache = res.cache
        tok = jnp.argmax(res.logits[:, 0], axis=-1)
        generated.append(int(tok[0]))

    np.testing.assert_array_equal(np.array(generated), hf_out[0, prompt.shape[1]:])
