"""Mixed-word serving over one resident base (ISSUE 12).

The contract under test: ONE engine holding base + stacked delta bank
serves W words through ONE compiled step program, and each word's responses
are BIT-FOR-BIT what a dedicated single-word engine (full finetuned params)
would have produced — tokens, lens probabilities, finish reasons.  Plus the
admission boundary (unknown words rejected explicitly), the loadgen word
mixing, and the bench_compare ``delta_switch`` regression gate.
"""

import json
import os
import sys

import pytest

from taboo_brittleness_tpu.runtime import aot
from taboo_brittleness_tpu.serve import loadgen
from taboo_brittleness_tpu.serve.scheduler import (
    Request, SlotScheduler, default_scenarios)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402

WORDS = ("ship", "moon")


def _requests(scenarios, words, n=6):
    prompts = ("Give me a hint", "Give me a clue about the word")
    names = ("chat", "sae_ablate", "projection", "chat_lens")
    # names advance every len(words) requests: n=8 covers every
    # (scenario, word) pair — chat_lens runs under BOTH words.
    return [Request(id=f"r{i:02d}", prompt=prompts[i % len(prompts)],
                    scenario=scenarios[names[(i // len(words)) % len(names)]],
                    seed=100 + i, word=words[i % len(words)])
            for i in range(n)]


def _drive(engine, scenarios, lens_target, requests):
    sched = SlotScheduler(engine, queue_limit=32, lens_target_id=lens_target)
    for req in requests:
        assert sched.submit(req), req.id
    return {r.id: r for r in sched.run_until_idle()}


@pytest.fixture(scope="module")
def multi_responses():
    """One mixed-word run over the multi engine, shared by the assertions."""
    aot.reset()
    engine, scenarios, tgt = loadgen.build_synthetic_multi_engine(words=WORDS)
    engine.warm_start()
    reqs = _requests(scenarios, WORDS, n=8)
    resps = _drive(engine, scenarios, tgt, reqs)
    return resps, dict(aot.stats().get("serve.step.multi", {})), engine.steps


def test_multi_word_matches_single_word_engines_bitwise(multi_responses):
    multi, _, _ = multi_responses
    for word in WORDS:
        engine, scenarios, tgt = loadgen.build_synthetic_engine(word=word)
        reqs = [r for r in _requests(scenarios, WORDS, n=8) if r.word == word]
        single = _drive(engine, scenarios, tgt, reqs)
        assert single, word
        for rid, want in single.items():
            got = multi[rid]
            assert got.word == word
            assert got.tokens == want.tokens, (rid, word)
            assert got.lens_probs == want.lens_probs, (rid, word)
            assert got.finish == want.finish and got.ok == want.ok


def test_multi_word_one_program_zero_aot_misses(multi_responses):
    resps, stats, steps = multi_responses
    assert len(resps) == 8 and all(r.ok for r in resps.values())
    assert stats["misses"] == 0 and stats["fallbacks"] == 0
    assert stats["programs"] == 1            # one executable, mixed traffic
    assert stats["hits"] == steps


def test_lens_readout_distinguishes_words(multi_responses):
    """Word routing is OBSERVABLE: the same chat_lens request served under
    different word_ids reads different lens probabilities (the tiny random
    model often ties on argmax tokens, the readout cannot)."""
    multi, _, _ = multi_responses
    by_word = {}
    for r in multi.values():
        if r.scenario == "chat_lens" and r.lens_probs:
            by_word.setdefault(r.word, r.lens_probs)
    assert set(by_word) == set(WORDS)
    assert by_word["ship"] != pytest.approx(by_word["moon"])


def test_unknown_word_rejected_at_submit():
    engine, scenarios, tgt = loadgen.build_synthetic_multi_engine(words=WORDS)
    sched = SlotScheduler(engine, queue_limit=8, lens_target_id=tgt)
    bad = Request(id="bad", prompt="hint", scenario=scenarios["chat"],
                  word="glass")
    assert not sched.submit(bad)
    assert sched.rejected == 1 and sched.queue_depth == 0
    # absent word -> the engine's word 0, accepted
    ok = Request(id="ok", prompt="hint", scenario=scenarios["chat"])
    assert sched.submit(ok)


def test_word_index_semantics():
    multi, _, _ = loadgen.build_synthetic_multi_engine(words=WORDS)
    assert multi.word_index(None) == 0
    assert multi.word_index("ship") == 0 and multi.word_index("moon") == 1
    assert multi.word_index("glass") is None
    single, _, _ = loadgen.build_synthetic_engine(word="moon")
    assert single.word_index(None) == 0
    assert single.word_index("moon") == 0    # its one resident checkpoint
    assert single.word_index("ship") is None


def test_admit_validates_word_id():
    engine, _, _ = loadgen.build_synthetic_multi_engine(words=WORDS)
    with pytest.raises(ValueError, match="word bank"):
        engine.admit(0, [1, 2, 3], max_new=2, word_id=len(WORDS))


def test_build_schedule_round_robins_words():
    scenarios = default_scenarios(max_new_tokens=4)
    plan = loadgen.build_schedule(
        6, seed=3, rate=100.0, mix={"chat": 1.0}, scenarios=scenarios,
        prompts=("p",), words=("a", "b", "c"))
    assert [req.word for _, req in plan] == ["a", "b", "c"] * 2
    plan = loadgen.build_schedule(
        3, seed=3, rate=100.0, mix={"chat": 1.0}, scenarios=scenarios,
        prompts=("p",))
    assert [req.word for _, req in plan] == [None] * 3


# ---------------------------------------------------------------------------
# bench_compare: the delta_switch regression gate.
# ---------------------------------------------------------------------------

def _write_round(tmp_path, n, extra):
    payload = {"n": n, "parsed": {"value": 20.0, **extra}}
    with open(str(tmp_path / f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(payload, f)


def test_bench_compare_delta_switch_within_band(tmp_path):
    _write_round(tmp_path, 1, {"delta_switch": {"switch_ms": 3.0,
                                                "delta_bytes_ratio": 0.32}})
    _write_round(tmp_path, 2, {"delta_switch": {"switch_ms": 4.0,
                                                "delta_bytes_ratio": 0.35}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions


def test_bench_compare_delta_switch_flags_regressions(tmp_path):
    _write_round(tmp_path, 1, {"delta_switch": {"switch_ms": 3.0,
                                                "delta_bytes_ratio": 0.32}})
    _write_round(tmp_path, 2, {"delta_switch": {"switch_ms": 9.0,
                                                "delta_bytes_ratio": 0.80}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("delta_switch.switch_ms" in r for r in regressions)
    assert any("delta_switch.delta_bytes_ratio" in r for r in regressions)


def test_bench_compare_delta_switch_missing_is_skipped(tmp_path):
    _write_round(tmp_path, 1, {"delta_switch": {"switch_ms": 3.0,
                                                "delta_bytes_ratio": 0.32}})
    _write_round(tmp_path, 2, {})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0
    assert any("delta_switch.switch_ms" in line and "skipped" in line
               for line in lines)
