"""Gemma-Scope grid sweeps + closed-loop attack search
(taboo_brittleness_tpu/grid, ISSUE 14).

Five layers:

- grid schema unit tests (GridSpec round-trip, tap-layer derivation,
  deterministic synthetic cell SAEs) — stdlib-fast;
- capture-parity tests for the multi-tap decode (runtime/decode.py): a
  1-tuple tap must be BIT-identical to the existing single-layer tap under
  every edit scenario (none / SAE ablation / projection — the PR-8
  cross-compilation hazard class), and a multi-layer tap on a ragged batch
  must reproduce each per-layer single tap slot for slot;
- capture/readout plumbing: the atomic residual artifact round-trips with
  its version header, and ``run_cell`` slices the right slot;
- the ISSUE 14 acceptance chaos e2e: 2 words x 2x2 grid through 2 real
  subprocess fleet workers with one injected worker DEATH — every cell
  commits exactly once, the breakage matrix is complete, and the merged
  events are green under the full ``trace_report --check`` gate including
  the new grid invariant;
- the deterministic attack search: same seed => byte-identical trajectory
  and breakage matrix, with at least one evolved forcing prefix scoring
  strictly higher than the seed population — plus the trace_report
  ``check_grid`` violation cases and the bench_compare grid gates.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

from taboo_brittleness_tpu.grid import runner as grid_runner
from taboo_brittleness_tpu.grid import search as grid_search
from taboo_brittleness_tpu.grid.spec import (
    GRID_ARTIFACT_VERSION, CellSpec, GridSpec, synthetic_cell_sae)
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.ops import projection
from taboo_brittleness_tpu.pipelines.interventions import (
    projection_edit, sae_ablation_edit)
from taboo_brittleness_tpu.runtime import decode, fleet, resilience, supervise
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer
from taboo_brittleness_tpu.serve import loadgen

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())
    yield
    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())


# ---------------------------------------------------------------------------
# GridSpec schema.
# ---------------------------------------------------------------------------

def test_grid_spec_build_and_roundtrip():
    spec = GridSpec.build([2, 1], [64, 32], release="synthetic")
    assert spec.tap_layers == (1, 2)            # sorted, unique
    assert len(spec.cells) == 4
    assert "L1-W32" in spec.keys and "L2-W64" in spec.keys
    cell = spec.cell("L2-W64")
    assert (cell.layer, cell.width) == (2, 64)
    assert spec.slot_of(cell) == 1
    again = GridSpec.from_dict(spec.to_dict())
    assert again == spec


def test_grid_spec_rejects_version_drift():
    spec = GridSpec.build([1], [32])
    d = spec.to_dict()
    d["version"] = GRID_ARTIFACT_VERSION + 1
    with pytest.raises(ValueError, match="version"):
        GridSpec.from_dict(d)


def test_grid_spec_artifact_dir_layout(tmp_path):
    spec = GridSpec.build([31], [16384], artifact_dir=str(tmp_path))
    assert spec.cells[0].path == str(tmp_path / "L31-W16k.npz")


def test_grid_spec_from_config_keeps_paper_cell():
    from taboo_brittleness_tpu.config import Config

    config = Config()
    spec = GridSpec.from_config(config)
    assert len(spec.cells) == 1
    assert spec.cells[0].sae_id == config.sae.sae_id
    assert spec.cells[0].layer == config.model.layer_idx
    # Widening the grid drops the single-cell sae_id passthrough.
    wide = GridSpec.from_config(config, layers=[1, 2], widths=[32])
    assert all(c.sae_id != config.sae.sae_id or c.layer == 31
               for c in wide.cells)
    assert len(wide.cells) == 2


def test_synthetic_cell_sae_is_cell_deterministic():
    a = synthetic_cell_sae(CellSpec(layer=1, width=32), 16, seed=7)
    b = synthetic_cell_sae(CellSpec(layer=1, width=32), 16, seed=7)
    c = synthetic_cell_sae(CellSpec(layer=2, width=32), 16, seed=7)
    np.testing.assert_array_equal(np.asarray(a.w_enc), np.asarray(b.w_enc))
    assert not np.array_equal(np.asarray(a.w_enc), np.asarray(c.w_enc))
    assert a.d_sae == 32


# ---------------------------------------------------------------------------
# Multi-tap capture parity (the PR-8 cross-compilation hazard class: a new
# static configuration must not perturb the captured bits).
# ---------------------------------------------------------------------------

def _tiny_model():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    return gemma2.init_params(jax.random.PRNGKey(0), cfg), cfg


def _decode_args(cfg, rows=2, T=5, ragged=False):
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(1, cfg.vocab_size,
                                 size=T - (i if ragged else 0)))
               for i in range(rows)]
    import jax.numpy as jnp

    padded, valid, positions = decode.pad_prompts(prompts)
    return (jnp.asarray(padded), jnp.asarray(valid), jnp.asarray(positions))


def _edit_for(scenario, cfg):
    import jax.numpy as jnp

    if scenario == "none":
        return {}
    if scenario == "sae":
        sae = synthetic_cell_sae(CellSpec(layer=1, width=32),
                                 cfg.hidden_size, seed=7)
        return {"edit_fn": sae_ablation_edit,
                "edit_params": {"sae": sae,
                                "latent_ids": jnp.asarray([0, 3], jnp.int32),
                                "layer": 1}}
    basis = projection.random_subspace(jax.random.PRNGKey(5),
                                       cfg.hidden_size, 2)
    return {"edit_fn": projection_edit,
            "edit_params": {"basis": basis, "layer": 1}}


@pytest.mark.parametrize("scenario", ["none", "sae", "projection"])
def test_multi_tap_1tuple_bit_identical_to_single_tap(scenario):
    params, cfg = _tiny_model()
    args = _decode_args(cfg)
    kw = dict(max_new_tokens=3, **_edit_for(scenario, cfg))
    single = decode.greedy_decode(params, cfg, *args,
                                  capture_residual_layer=1, **kw)
    multi = decode.greedy_decode(params, cfg, *args,
                                 capture_residual_layer=(1,), **kw)
    np.testing.assert_array_equal(np.asarray(single.tokens),
                                  np.asarray(multi.tokens))
    assert np.asarray(multi.residual).shape[0] == 1
    # Bit identity, not allclose: the tuple path must compile to the exact
    # same per-slot select as the int path.
    np.testing.assert_array_equal(np.asarray(single.residual),
                                  np.asarray(multi.residual)[0])


def test_multi_tap_matches_per_layer_single_taps_ragged():
    params, cfg = _tiny_model()
    args = _decode_args(cfg, rows=3, T=6, ragged=True)
    taps = (1, 2)
    multi = decode.greedy_decode(params, cfg, *args, max_new_tokens=3,
                                 capture_residual_layer=taps)
    stack = np.asarray(multi.residual)
    assert stack.shape[0] == len(taps)
    for k, layer in enumerate(taps):
        single = decode.greedy_decode(params, cfg, *args, max_new_tokens=3,
                                      capture_residual_layer=layer)
        # Tokens stay bit-identical (the decode path itself is untouched by
        # how many taps ride the carry) ...
        np.testing.assert_array_equal(np.asarray(single.tokens),
                                      np.asarray(multi.tokens))
        # ... but a K>1 carry is a DIFFERENT program, and XLA refuses the
        # forward around the extra consumer: slot values match to float
        # precision, not bit-for-bit (the K=1 test above holds the bit
        # contract against the int path).
        np.testing.assert_allclose(np.asarray(single.residual), stack[k],
                                   rtol=1e-4, atol=1e-5)


def test_multi_tap_rejects_duplicate_layers():
    params, cfg = _tiny_model()
    args = _decode_args(cfg)
    with pytest.raises(ValueError, match="duplicate"):
        decode.greedy_decode(params, cfg, *args, max_new_tokens=2,
                             capture_residual_layer=(1, 1))


def test_generate_normalizes_list_taps():
    """``generate`` accepts a list of taps (CLI plumbing) and rides the same
    static-tuple path — result stacked [K, B, T, D]."""
    params, cfg = _tiny_model()
    tok = WordTokenizer(["ship", "hint"], vocab_size=cfg.vocab_size)
    res, texts, seqs = decode.generate(
        params, cfg, tok, ["Give me a hint"], max_new_tokens=3,
        capture_residual_layer=[2, 1], return_texts=False)
    assert np.asarray(res.residual).shape[0] == 2
    assert np.asarray(res.residual).shape[3] == cfg.hidden_size


# ---------------------------------------------------------------------------
# Capture artifact + per-cell unit.
# ---------------------------------------------------------------------------

def _captured_grid(tmp_path, words=("ship",), max_new=3):
    params, cfg = _tiny_model()
    spec = GridSpec.build([1, 2], [32, 64], release="synthetic")
    tok = WordTokenizer(
        list(words) + ["Give", "me", "a", "hint", "about", "the", "word"],
        vocab_size=cfg.vocab_size)
    resid_dir = str(tmp_path / "residuals")
    for w in words:
        grid_runner.capture_word_residuals(
            params, cfg, tok, w, spec, max_new_tokens=max_new,
            resid_dir=resid_dir)
    return params, cfg, tok, spec, resid_dir


def test_capture_artifact_roundtrip_and_header(tmp_path):
    _params, cfg, _tok, spec, resid_dir = _captured_grid(tmp_path)
    path = grid_runner.residual_path(resid_dir, "ship")
    art = grid_runner.load_word_residuals(path)
    K, B, T, D = art["residual"].shape
    assert K == len(spec.tap_layers) and D == cfg.hidden_size
    assert art["mask"].shape == (B, T)
    assert tuple(int(x) for x in art["tap_layers"]) == spec.tap_layers
    # Version drift fails loudly.
    blob = dict(np.load(path))
    blob["__grid_version__"] = np.int64(GRID_ARTIFACT_VERSION + 1)
    np.savez(path, **blob)
    with pytest.raises(ValueError, match="version"):
        grid_runner.load_word_residuals(path)


def test_run_cell_readout_and_scoring(tmp_path):
    params, cfg, tok, spec, resid_dir = _captured_grid(tmp_path)
    unit = grid_runner.grid_units(spec, ["ship"])[0]
    res = grid_runner.run_cell(unit, spec=spec, resid_dir=resid_dir,
                               model=(params, cfg, tok), seed=7, top_k=4,
                               max_new_tokens=3)
    assert res["word"] == "ship" and res["cell"] == unit["readout"]["key"]
    assert len(res["top_latents"]) == 4
    assert {"leak_base", "leak_ablated", "broke"} <= set(res)
    # Readout-only mode (no model) still yields the latent readout.
    lite = grid_runner.run_cell(unit, spec=spec, resid_dir=resid_dir,
                                model=None, seed=7, top_k=4)
    assert lite["top_latents"] == res["top_latents"]


def test_run_cell_rejects_untapped_layer(tmp_path):
    # Capture with taps (1, 2), then ask for a cell at layer 3 through a
    # WIDER spec: the stale-artifact guard must refuse, not mis-slice.
    _params, _cfg, _tok, _spec, resid_dir = _captured_grid(tmp_path)
    wide = GridSpec.build([3], [32], release="synthetic")
    unit = grid_runner.grid_units(wide, ["ship"])[0]
    with pytest.raises(ValueError, match="not in captured taps"):
        grid_runner.run_cell(unit, spec=wide, resid_dir=resid_dir)


# ---------------------------------------------------------------------------
# ISSUE 14 acceptance: grid e2e through real fleet workers, worker death.
# ---------------------------------------------------------------------------

def test_grid_fleet_worker_death_exactly_once(tmp_path):
    """2 words x 2x2 grid = 8 cells over 2 real subprocess workers; worker
    ``w1`` dies at its first commit.  Every cell must commit exactly once,
    the breakage matrix must be complete, and the merged event stream must
    be green under the full trace_report gate including ``check_grid``."""
    out = str(tmp_path / "grid")
    words = ["ship", "moon"]
    _params, _cfg, _tok, spec, resid_dir = _captured_grid(
        tmp_path / "grid", words=tuple(words))
    units = grid_runner.grid_units(spec, words)
    plan = {"fleet.commit": [
        {"mode": "die", "times": 1, "match": "w1", "incarnation": 0}]}
    env = {"JAX_PLATFORMS": "cpu", "TABOO_FAULT_PLAN": json.dumps(plan),
           "TBX_OBS_PROGRESS_S": "0.2", "TBX_SUPERVISE_BACKOFF_S": "0"}

    def argv(wid):
        return [sys.executable, "-m", "taboo_brittleness_tpu", "worker",
                "--fleet-dir", out, "--worker-id", wid]

    res = fleet.run_fleet(
        units, out, n_workers=2, worker_argv=argv, worker_env=env,
        spool_config={"mode": "grid", "words": words,
                      "grid": spec.to_dict(), "resid_dir": resid_dir,
                      "seed": 7, "top_k": 4, "max_new_tokens": 3},
        lease_s=3.0, poll_s=0.2, supervise_poll=0.2, grace=2.0,
        wedge_after=30.0, max_incarnations=4, spec_factor=0.0,
        policy=fleet.RetryPolicy(max_retries=6, base_delay=0.0),
        max_wall_s=600.0)

    assert res.status == "done", res.to_dict()
    spool = fleet.FleetSpool(os.path.join(out, fleet.SPOOL_DIRNAME))
    assert sorted(spool.done_uids()) == sorted(u["uid"] for u in units)
    assert res.committed == len(units) and res.quarantined == 0
    # The death burned an incarnation and its unit was re-issued.
    incs = {w["worker_id"]: w["incarnations"] for w in res.workers}
    assert incs["w1"] >= 2, incs
    assert res.lease_expiries >= 1 and res.reissued >= 1, res.to_dict()

    matrix = grid_runner.assemble_matrix(out, spec, words)
    assert matrix["complete"], matrix
    for w in words:
        for key in spec.keys:
            cell = matrix["matrix"][w][key]
            assert cell["status"] == "done"
            assert cell["top_latents"], cell
    pools = grid_runner.latent_pools(matrix)
    assert set(pools) == set(spec.keys)

    merged = os.path.join(out, "_events.jsonl")
    events = list(trace_report.iter_events(merged))
    assert trace_report.check(merged) == []
    assert trace_report.check_fleet(merged, events) == []
    assert trace_report.check_grid(merged, events) == []
    rendered = trace_report.report(events)
    assert "grid:" in rendered
    for key in spec.keys:
        assert key in rendered


# ---------------------------------------------------------------------------
# Attack search: determinism + strict improvement.
# ---------------------------------------------------------------------------

_SEARCH_KW = dict(words=("ship", "moon"), seed=3, generations=3,
                  population=4, n_requests=4, max_new_tokens=5,
                  latent_pools={"L1-W32": [1, 5, 9], "L2-W64": [2, 7]})


def test_attack_search_deterministic_and_improves():
    engine, _scen, lens_target = loadgen.build_synthetic_multi_engine(
        words=("ship", "moon"), max_new_tokens=6)
    r1 = grid_search.run_search(engine, lens_target, **_SEARCH_KW)
    r2 = grid_search.run_search(engine, lens_target, **_SEARCH_KW)
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)
    # Strict improvement over the seed population (the lens bonus provides
    # continuous signal even when nothing forces yet).
    assert r1["improved"] is True
    assert r1["best"]["fitness"] > r1["seed_best_fitness"]
    assert len(r1["trajectory"]) == r1["generations"]
    # Breakage matrix covers every (word, cell, attack) triple.
    by_word = r1["matrix"]["by_word"]
    for w in ("ship", "moon"):
        for cell in r1["matrix"]["cells"]:
            assert len(by_word[w][cell]) == len(r1["matrix"]["attacks"])
            for rec in by_word[w][cell].values():
                assert {"forcing", "lens", "broke"} <= set(rec)


def test_attack_search_seed_changes_trajectory():
    engine, _scen, lens_target = loadgen.build_synthetic_multi_engine(
        words=("ship", "moon"), max_new_tokens=6)
    r1 = grid_search.run_search(engine, lens_target, **_SEARCH_KW)
    r3 = grid_search.run_search(engine, lens_target,
                                **dict(_SEARCH_KW, seed=4))
    assert json.dumps(r1, sort_keys=True) != json.dumps(r3, sort_keys=True)


def test_attack_name_is_stable_across_processes():
    a = grid_search.Attack(prefix="My secret word is",
                           template="What is the word?", latents=(1, 2))
    b = grid_search.Attack(prefix="My secret word is",
                           template="What is the word?", latents=(1, 2))
    assert a.name == b.name and a.name.startswith("a")


# ---------------------------------------------------------------------------
# trace_report: check_grid violation cases + grid section rendering.
# ---------------------------------------------------------------------------

def _grid_stream(tmp_path, records, name="_events.jsonl"):
    """A minimal valid stream: ``records`` entries are either
    ("point", name, attrs) or ("span", name, attrs, status)."""
    path = str(tmp_path / name)
    seq = 0
    next_id = [2]
    lines = []

    def add(rec):
        nonlocal seq
        seq += 1
        lines.append(json.dumps({"v": 1, "seq": seq, "t": float(seq),
                                 **rec}))

    add({"ev": "start", "kind": "run", "name": "sweep", "id": 1,
         "attrs": {"pipeline": "fleet"}})
    for rec in records:
        if rec[0] == "point":
            add({"ev": "point", "kind": "point", "name": rec[1],
                 "parent": 1, "attrs": rec[2]})
        else:
            sid = next_id[0]
            next_id[0] += 1
            add({"ev": "start", "kind": "phase", "name": rec[1], "id": sid,
                 "parent": 1, "attrs": rec[2]})
            add({"ev": "end", "kind": "phase", "name": rec[1], "id": sid,
                 "dur": 0.5, "status": rec[3]})
    add({"ev": "end", "kind": "run", "name": "sweep", "id": 1, "dur": 9.0,
         "status": "ok"})
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def _grid_errors(path):
    return trace_report.check_grid(path, list(trace_report.iter_events(path)))


def test_check_grid_green_on_clean_cell(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "ship@L1-W32", "worker": "w0"}),
        ("span", "grid.cell", {"word": "ship", "cell": "L1-W32"}, "ok"),
        ("point", "fleet.commit", {"uid": "ship@L1-W32", "worker": "w0",
                                   "duplicate": False}),
        ("point", "fleet.exit", {"status": "done"}),
    ])
    assert _grid_errors(path) == []


def test_check_grid_flags_double_commit(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "ship@L1-W32", "worker": "w0"}),
        ("span", "grid.cell", {"word": "ship", "cell": "L1-W32"}, "ok"),
        ("point", "fleet.commit", {"uid": "ship@L1-W32", "worker": "w0",
                                   "duplicate": False}),
        ("point", "fleet.commit", {"uid": "ship@L1-W32", "worker": "w1",
                                   "duplicate": False}),
        ("point", "fleet.exit", {"status": "done"}),
    ])
    assert any("exactly-once violated" in e for e in _grid_errors(path))


def test_check_grid_flags_commit_without_span(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "ship@L1-W32", "worker": "w0"}),
        ("point", "fleet.commit", {"uid": "ship@L1-W32", "worker": "w0",
                                   "duplicate": False}),
        ("point", "fleet.exit", {"status": "done"}),
    ])
    assert any("no completed grid.cell span" in e for e in _grid_errors(path))


def test_check_grid_flags_unresolved_cell(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "ship@L1-W32", "worker": "w0"}),
        ("point", "fleet.exit", {"status": "done"}),
    ])
    assert any("never committed or quarantined" in e
               for e in _grid_errors(path))


def test_check_grid_drained_run_tolerates_unresolved(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "ship@L1-W32", "worker": "w0"}),
        ("point", "fleet.exit", {"status": "drained"}),
    ])
    assert _grid_errors(path) == []


def test_check_grid_noop_on_non_grid_fleet_stream(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "word00-L1", "worker": "w0"}),
        ("point", "fleet.exit", {"status": "done"}),
    ])
    assert _grid_errors(path) == []


def test_grid_section_renders_cell_lanes(tmp_path):
    path = _grid_stream(tmp_path, [
        ("point", "fleet.claim", {"uid": "ship@L1-W32", "worker": "w0"}),
        ("span", "grid.cell", {"word": "ship", "cell": "L1-W32"}, "error"),
        ("span", "grid.cell", {"word": "ship", "cell": "L1-W32"}, "ok"),
        ("point", "fleet.commit", {"uid": "ship@L1-W32", "worker": "w0",
                                   "duplicate": False}),
        ("point", "fleet.exit", {"status": "done"}),
    ])
    out = trace_report.report(list(trace_report.iter_events(path)))
    assert "grid:" in out
    assert "L1-W32" in out
    # Two runs (one errored retry), one commit.
    line = next(ln for ln in out.splitlines() if "L1-W32" in ln)
    cols = line.split()
    assert cols[1:6] == ["1", "2", "1", "1", "0"]


# ---------------------------------------------------------------------------
# bench_compare: the grid_sweep / attack_search regression gates.
# ---------------------------------------------------------------------------

def _write_round(tmp_path, n, extra):
    payload = {"n": n, "parsed": {"value": 20.0, **extra}}
    with open(str(tmp_path / f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(payload, f)


def test_bench_compare_grid_within_band(tmp_path):
    _write_round(tmp_path, 1, {"grid_sweep": {"cells_per_hour": 4000.0},
                               "attack_search": {"break_rate": 0.0}})
    _write_round(tmp_path, 2, {"grid_sweep": {"cells_per_hour": 3500.0},
                               "attack_search": {"break_rate": 0.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions


def test_bench_compare_grid_flags_throughput_regression(tmp_path):
    _write_round(tmp_path, 1, {"grid_sweep": {"cells_per_hour": 4000.0}})
    _write_round(tmp_path, 2, {"grid_sweep": {"cells_per_hour": 2500.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("grid_sweep.cells_per_hour" in r for r in regressions)


def test_bench_compare_break_rate_slack_tolerates_near_zero(tmp_path):
    # 0.02 -> 0.0 is within the 0.05 absolute slack: near-zero wiggle.
    _write_round(tmp_path, 1, {"attack_search": {"break_rate": 0.02}})
    _write_round(tmp_path, 2, {"attack_search": {"break_rate": 0.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions


def test_bench_compare_break_rate_flags_real_regression(tmp_path):
    _write_round(tmp_path, 1, {"attack_search": {"break_rate": 0.5}})
    _write_round(tmp_path, 2, {"attack_search": {"break_rate": 0.2}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("attack_search.break_rate" in r for r in regressions)


def test_bench_compare_grid_missing_is_skipped(tmp_path):
    _write_round(tmp_path, 1, {"grid_sweep": {"cells_per_hour": 4000.0}})
    _write_round(tmp_path, 2, {})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0
    assert any("grid_sweep.cells_per_hour" in line and "skipped" in line
               for line in lines)
