"""Elastic fleet execution (runtime/fleet.py, ISSUE 10).

Four layers:

- spool/lease/commit unit tests (claim-by-rename, exclusion lists,
  first-writer-wins, lease renewal) — stdlib-fast;
- satellite tests: FailureLedger v3 worker stamps + v2→v3 normalization,
  per-worker telemetry file suffixes, the preemption-notice guard, the
  supervisor's fleet-worker mode, trace_report fleet invariants, and the
  bench_compare ``fleet_recovery`` gate;
- fast fleet integrations over stdlib-only FAKE workers (real subprocesses,
  real supervision and leases, trivial unit compute): drain → resume, and
  straggler speculation with a benign duplicate commit;
- the ISSUE 10 acceptance chaos e2e on the real tiny-model synthetic
  workers: 3 subprocess workers over 12 words, one worker ``die``d mid-word
  and one wedged — every word completes exactly once, zero ``.corrupt``
  files, the merged ``_events.jsonl`` is green under ``trace_report
  --check``, and the killed worker's unit shows a lease-expiry → re-issue
  chain in the merged ledger.
"""

import json
import os
import sys
import threading
import time

import pytest

from taboo_brittleness_tpu.runtime import fleet, resilience, supervise
from taboo_brittleness_tpu.runtime.fleet import (
    FleetSpool, LeaseKeeper, holder_token, unit_id)
from taboo_brittleness_tpu.runtime.resilience import (
    FailureLedger, RetryPolicy)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "obs", "fleet")

sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402
import trace_report  # noqa: E402

FAST = RetryPolicy(max_retries=6, base_delay=0.0)


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())
    monkeypatch.delenv("TBX_WORKER_ID", raising=False)
    yield
    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())


def _spool(tmp_path) -> FleetSpool:
    return FleetSpool(str(tmp_path / "spool")).ensure()


# ---------------------------------------------------------------------------
# Spool: claim-by-rename, exclusion, first-writer-wins, leases.
# ---------------------------------------------------------------------------

def test_unit_id_is_filesystem_safe():
    assert unit_id("ship", {"layer": 31}) == "ship@L31"
    assert "/" not in unit_id("a/b c", {"key": "16k/L9"})
    assert unit_id("ship", {}) == "ship@r0"


def test_claim_respects_exclusion_and_order(tmp_path):
    sp = _spool(tmp_path)
    sp.put("u0", {"word": "a"}, attempt=1, excluded=["w1-i0"])
    sp.put("u1", {"word": "b"})
    rec = sp.claim("w1-i0", "w1")
    assert rec["uid"] == "u1"              # u0 excludes this holder
    rec2 = sp.claim("w2-i0", "w2")
    assert rec2["uid"] == "u0"             # a different holder may take it
    assert rec2["attempt"] == 1
    assert sp.claim("w2-i0", "w2") is None
    # Claimed markers carry (uid, attempt, holder) for postmortems.
    holders = {c["holder"] for c in sp.claimed_entries()}
    assert holders == {"w1-i0", "w2-i0"}


def test_claim_garbage_collects_resolved_units(tmp_path):
    sp = _spool(tmp_path)
    sp.put("u0", {"word": "a"})
    assert sp.commit("u0", {"result": 1}, holder="w0-i0")
    # A stale speculative re-issue of the already-committed unit:
    sp.put("u0", {"word": "a"}, attempt=1)
    assert sp.claim("w1-i0", "w1") is None  # skipped AND removed
    assert sp.pending() == []


def test_claim_fault_site_fires(tmp_path):
    sp = _spool(tmp_path)
    sp.put("u0", {"word": "a"})
    inj = resilience.FaultInjector()
    inj.arm("fleet.claim", mode="fail", times=1)
    resilience.set_injector(inj)
    with pytest.raises(resilience.InjectedFault):
        sp.claim("w0-i0", "w0")
    assert sp.claim("w0-i0", "w0")["uid"] == "u0"   # next attempt succeeds


def test_commit_first_writer_wins(tmp_path):
    sp = _spool(tmp_path)
    assert sp.commit("u0", {"result": "first"}, holder="w0-i0") is True
    assert sp.commit("u0", {"result": "second"}, holder="w1-i0") is False
    with open(sp.done_path("u0")) as f:
        assert json.load(f)["result"] == "first"
    assert sp.duplicate_count() == 1
    assert sp.done_uids() == ["u0"]


def test_lease_keeper_renews_and_preserves_claim_time(tmp_path):
    sp = _spool(tmp_path)
    keeper = LeaseKeeper(sp, "u0", 0, "w0-i0", "w0", lease_s=0.3).start()
    try:
        first = sp.leases()[0]
        time.sleep(0.35)
        renewed = sp.leases()[0]
    finally:
        keeper.stop()
    assert renewed["renewed_at"] > first["renewed_at"]
    assert renewed["claimed_at"] == first["claimed_at"]
    assert renewed["expires_at"] > first["expires_at"]
    assert sp.leases() == []               # stop() releases the lease


def test_lease_renew_fault_lets_lease_expire(tmp_path):
    sp = _spool(tmp_path)
    inj = resilience.FaultInjector()
    inj.arm("fleet.lease_renew", mode="fail", times=None)
    resilience.set_injector(inj)
    keeper = LeaseKeeper(sp, "u0", 0, "w0-i0", "w0", lease_s=0.3).start()
    try:
        first = sp.leases()[0]
        time.sleep(0.45)
        stale = sp.leases()[0]
    finally:
        keeper.stop()
    # Every renewal faulted: expires_at never advanced past the claim-time
    # lease — the coordinator will expire and re-issue, which is benign.
    assert stale["expires_at"] == first["expires_at"]


def test_percentile():
    assert fleet._percentile([], 75) == 0.0
    assert fleet._percentile([1.0], 75) == 1.0
    assert fleet._percentile([1, 2, 3, 4], 75) == 3


# ---------------------------------------------------------------------------
# Satellite: FailureLedger v3 — worker stamps + v2→v3 normalization.
# ---------------------------------------------------------------------------

def test_ledger_v3_stamps_worker(tmp_path):
    path = str(tmp_path / "_failures.json")
    led = FailureLedger(path=path, worker="w7")
    led.record_retry("ship", "decode", OSError("x"), 1)
    led.record_quarantine("moon", "decode", OSError("y"), 3)
    with open(path) as f:
        data = json.load(f)
    assert data["version"] == 3
    assert data["worker"] == "w7"
    assert data["retried"]["ship"]["worker"] == "w7"
    assert data["quarantined"]["moon"]["worker"] == "w7"


def test_ledger_without_worker_emits_no_worker_keys(tmp_path):
    """Standalone (non-fleet) ledgers read exactly as v2 did, modulo the
    version bump — no worker noise."""
    path = str(tmp_path / "_failures.json")
    led = FailureLedger(path=path)
    led.record_retry("ship", "decode", OSError("x"), 1)
    with open(path) as f:
        data = json.load(f)
    assert "worker" not in data
    assert data["retried"]["ship"] == {"attempts": 1, "incarnation": 0}


def test_ledger_v2_to_v3_normalization(tmp_path, monkeypatch):
    """A v2 ledger (no worker stamps) loaded by a resume incarnation keeps
    its entries unforged; a prior file that DID carry a top-level worker
    propagates it onto its unstamped entries."""
    path = str(tmp_path / "_failures.json")
    with open(path, "w") as f:
        json.dump({"version": 2, "incarnation": 0,
                   "retried": {"ship": {"attempts": 2, "incarnation": 0}},
                   "quarantined": {}}, f)
    led = FailureLedger(path=path, incarnation=1, worker="w1")
    assert led.retried == {"ship": {"attempts": 2, "incarnation": 0}}
    led.record_retry("moon", "decode", OSError("x"), 1)
    assert led.retried["moon"]["worker"] == "w1"

    with open(path, "w") as f:
        json.dump({"version": 3, "incarnation": 0, "worker": "w0",
                   "retried": {"ship": {"attempts": 2, "incarnation": 0}},
                   "quarantined": {}}, f)
    led2 = FailureLedger(path=path, incarnation=1, worker="w1")
    assert led2.retried["ship"]["worker"] == "w0"


# ---------------------------------------------------------------------------
# Satellite: per-worker telemetry files + worker stamps + progress.
# ---------------------------------------------------------------------------

def test_sweep_observer_uses_worker_suffixed_files(tmp_path, monkeypatch):
    from taboo_brittleness_tpu import obs

    monkeypatch.setenv("TBX_WORKER_ID", "alpha")
    out = str(tmp_path)
    with obs.sweep_observer(out, pipeline="fleet-worker",
                            words=["u0"]) as ob:
        with ob.word("u0"):
            pass
    assert os.path.exists(os.path.join(out, "_events.alpha.jsonl"))
    assert os.path.exists(os.path.join(out, "_progress.alpha.json"))
    assert not os.path.exists(os.path.join(out, "_events.jsonl"))
    events = [json.loads(line) for line in
              open(os.path.join(out, "_events.alpha.jsonl"))]
    # Every event is stamped top-level with the worker; the run span also
    # carries it as an attr (the per-worker lane key).
    assert all(e.get("worker") == "alpha" for e in events)
    run_starts = [e for e in events
                  if e.get("ev") == "start" and e.get("kind") == "run"]
    assert run_starts[0]["attrs"]["worker"] == "alpha"
    with open(os.path.join(out, "_progress.alpha.json")) as f:
        assert json.load(f)["worker"] == "alpha"


# ---------------------------------------------------------------------------
# Satellite: the preemption-notice guard.
# ---------------------------------------------------------------------------

def test_preempt_notice_guard_gauge_warn_and_manifest(tmp_path, monkeypatch):
    from taboo_brittleness_tpu import obs
    from taboo_brittleness_tpu.obs import metrics as obs_metrics
    from taboo_brittleness_tpu.runtime.manifest import RunManifest

    obs_metrics.reset()
    monkeypatch.setenv("TBX_PREEMPT_NOTICE_S", "0.05")
    out = str(tmp_path)
    with obs.sweep_observer(out, pipeline="test", words=["slow"]) as ob:
        with ob.word("slow"):
            time.sleep(0.12)               # outlives the 0.05s notice
    assert ob.preempt_margin_s is not None and ob.preempt_margin_s < 0
    snap = obs_metrics.snapshot()
    assert snap["gauges"]["sweep.preempt_margin_s"] == ob.preempt_margin_s
    events = [json.loads(line) for line in
              open(os.path.join(out, "_events.jsonl"))]
    warns = [e for e in events
             if e.get("name") == "sweep.preempt_notice_exceeded"]
    assert warns and warns[0]["attrs"]["word"] == "slow"
    # The manifest hoists the gauge to a first-class field.
    manifest = RunManifest(command="test")
    assert manifest.to_dict()["preempt_margin_s"] == ob.preempt_margin_s
    obs_metrics.reset()


def test_preempt_margin_positive_within_notice(tmp_path, monkeypatch):
    from taboo_brittleness_tpu import obs
    from taboo_brittleness_tpu.obs import metrics as obs_metrics

    obs_metrics.reset()
    monkeypatch.setenv("TBX_PREEMPT_NOTICE_S", "30")
    with obs.sweep_observer(str(tmp_path), pipeline="test",
                            words=["fast"]) as ob:
        with ob.word("fast"):
            pass
    assert ob.preempt_margin_s is not None and ob.preempt_margin_s > 0
    events = [json.loads(line) for line in
              open(os.path.join(str(tmp_path), "_events.jsonl"))]
    assert not any(e.get("name") == "sweep.preempt_notice_exceeded"
                   for e in events)
    obs_metrics.reset()


# ---------------------------------------------------------------------------
# Satellite: supervise's fleet-worker mode (per-worker filenames).
# ---------------------------------------------------------------------------

_WORKER_FAKE_CHILD = r"""
import json, os, sys, time

out = sys.argv[1]
wid = os.environ["TBX_WORKER_ID"]
tmp = os.path.join(out, "tmp")
with open(tmp, "w") as f:
    json.dump({"v": 1, "pid": os.getpid(), "updated_at": time.time(),
               "heartbeat_seconds": 0.05, "status": "done",
               "worker": wid,
               "incarnation": int(os.environ.get("TBX_INCARNATION", "0"))},
              f)
os.replace(tmp, os.path.join(out, f"_progress.{wid}.json"))
sys.exit(0)
"""


def test_supervise_worker_mode_uses_per_worker_files(tmp_path):
    out = str(tmp_path / "out")
    os.makedirs(out)
    child = str(tmp_path / "child.py")
    with open(child, "w") as f:
        f.write(_WORKER_FAKE_CHILD)
    res = supervise.supervise(
        [sys.executable, child, out], out, worker_id="wk",
        max_incarnations=2, poll_interval=0.02, grace=0.5,
        wedge_after=1.0, policy=FAST)
    assert res.ok
    assert os.path.exists(os.path.join(out, "_supervise.wk.json"))
    assert not os.path.exists(os.path.join(out, "_supervise.json"))
    assert os.path.exists(os.path.join(out, "_progress.wk.json"))
    events = [json.loads(line) for line in
              open(os.path.join(out, "_events.wk.jsonl"))]
    launches = [e for e in events if e.get("name") == "supervise.launch"]
    assert launches and launches[0]["attrs"]["worker"] == "wk"
    assert not os.path.exists(os.path.join(out, "_events.jsonl"))


# ---------------------------------------------------------------------------
# trace_report: fleet invariants + per-worker lane rendering.
# ---------------------------------------------------------------------------

def test_committed_fleet_fixture_is_green():
    path = os.path.join(FIXTURE_DIR, "_events.jsonl")
    events = list(trace_report.iter_events(path))
    assert trace_report.check(path) == []
    assert trace_report.check_fleet(path, events) == []


def test_fleet_fixture_renders_worker_lanes():
    path = os.path.join(FIXTURE_DIR, "_events.jsonl")
    out = trace_report.report(list(trace_report.iter_events(path)))
    assert "fleet:" in out
    assert "w1" in out and "dropped_leases" in out
    assert "lease expired" in out and "re-issued" in out


def _fleet_stream(tmp_path, points, name="_events.jsonl"):
    """A minimal valid fleet event stream wrapping ``points``."""
    path = str(tmp_path / name)
    seq = 0
    lines = []

    def add(rec):
        nonlocal seq
        seq += 1
        lines.append(json.dumps({"v": 1, "seq": seq, "t": float(seq),
                                 **rec}))

    add({"ev": "start", "kind": "run", "name": "sweep", "id": 1,
         "attrs": {"pipeline": "fleet"}})
    for name_, attrs in points:
        add({"ev": "point", "kind": "point", "name": name_, "parent": 1,
             "attrs": attrs})
    add({"ev": "end", "kind": "run", "name": "sweep", "id": 1, "dur": 1.0,
         "status": "ok"})
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def test_check_fleet_flags_double_commit(tmp_path):
    path = _fleet_stream(tmp_path, [
        ("fleet.claim", {"uid": "u0", "worker": "w0"}),
        ("fleet.commit", {"uid": "u0", "worker": "w0", "duplicate": False}),
        ("fleet.commit", {"uid": "u0", "worker": "w1", "duplicate": False}),
        ("fleet.exit", {"status": "done"}),
    ])
    errors = trace_report.check_fleet(path,
                                      list(trace_report.iter_events(path)))
    assert any("first-writer-wins" in e for e in errors)


def test_check_fleet_flags_unresolved_claim(tmp_path):
    path = _fleet_stream(tmp_path, [
        ("fleet.claim", {"uid": "u0", "worker": "w0"}),
        ("fleet.exit", {"status": "done"}),
    ])
    errors = trace_report.check_fleet(path,
                                      list(trace_report.iter_events(path)))
    assert any("never committed or quarantined" in e for e in errors)


def test_check_fleet_drained_run_tolerates_unresolved(tmp_path):
    path = _fleet_stream(tmp_path, [
        ("fleet.claim", {"uid": "u0", "worker": "w0"}),
        ("fleet.lease_expired", {"uid": "u0", "holder": "w0-i0"}),
        ("fleet.exit", {"status": "drained"}),
    ])
    assert trace_report.check_fleet(
        path, list(trace_report.iter_events(path))) == []


def test_check_fleet_flags_expiry_without_reissue(tmp_path):
    path = _fleet_stream(tmp_path, [
        ("fleet.claim", {"uid": "u0", "worker": "w0"}),
        ("fleet.claim", {"uid": "u1", "worker": "w1"}),
        ("fleet.commit", {"uid": "u0", "worker": "w0", "duplicate": False}),
        ("fleet.commit", {"uid": "u1", "worker": "w1", "duplicate": False}),
        ("fleet.lease_expired", {"uid": "u2", "holder": "w2-i0"}),
        ("fleet.exit", {"status": "done"}),
    ])
    errors = trace_report.check_fleet(path,
                                      list(trace_report.iter_events(path)))
    assert any("never resolved to a re-issue" in e for e in errors)


def test_check_fleet_flags_nonmonotone_worker_stream(tmp_path):
    path = _fleet_stream(tmp_path, [
        ("fleet.claim", {"uid": "u0", "worker": "w0"}),
        ("fleet.commit", {"uid": "u0", "worker": "w0", "duplicate": False}),
        ("fleet.exit", {"status": "done"}),
    ])
    with open(str(tmp_path / "_events.w0.jsonl"), "w") as f:
        f.write(json.dumps({"v": 1, "seq": 5, "t": 0.0, "ev": "point",
                            "kind": "point", "name": "x"}) + "\n")
        f.write(json.dumps({"v": 1, "seq": 3, "t": 0.1, "ev": "point",
                            "kind": "point", "name": "y"}) + "\n")
    errors = trace_report.check_fleet(path,
                                      list(trace_report.iter_events(path)))
    assert any("worker stream seq" in e for e in errors)


def test_check_fleet_noop_on_non_fleet_stream():
    """The supervised-run fixture has no fleet events and no sibling worker
    streams in its directory — the fleet gate must stay silent there."""
    path = os.path.join(REPO, "tests", "fixtures", "obs", "_events.jsonl")
    assert trace_report.check_fleet(
        path, list(trace_report.iter_events(path))) == []


# ---------------------------------------------------------------------------
# bench_compare: the fleet_recovery regression gate.
# ---------------------------------------------------------------------------

def _write_round(tmp_path, n, extra):
    payload = {"n": n, "parsed": {"value": 20.0, **extra}}
    with open(str(tmp_path / f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(payload, f)


def test_bench_compare_fleet_recovery_within_band(tmp_path):
    _write_round(tmp_path, 1, {"fleet_recovery": {"recovery_seconds": 4.0}})
    _write_round(tmp_path, 2, {"fleet_recovery": {"recovery_seconds": 5.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions


def test_bench_compare_fleet_recovery_flags_regression(tmp_path):
    _write_round(tmp_path, 1, {"fleet_recovery": {"recovery_seconds": 4.0}})
    _write_round(tmp_path, 2, {"fleet_recovery": {"recovery_seconds": 9.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("fleet_recovery.recovery_seconds" in r for r in regressions)


def test_bench_compare_fleet_recovery_missing_is_skipped(tmp_path):
    _write_round(tmp_path, 1, {"fleet_recovery": {"recovery_seconds": 4.0}})
    _write_round(tmp_path, 2, {})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0
    assert any("fleet_recovery.recovery_seconds" in line and "skipped" in line
               for line in lines)


# ---------------------------------------------------------------------------
# Fast fleet integrations over stdlib-only fake workers.
# ---------------------------------------------------------------------------

_FAKE_WORKER = r"""
import sys, time
sys.path.insert(0, {repo!r})
from taboo_brittleness_tpu.runtime import fleet, supervise

supervise.install_drain_handlers()


def unit_fn(unit):
    time.sleep(float(unit.get("sleep", 0.05)))
    return {{"word": unit.get("word"), "ok": True}}


res = fleet.run_worker(sys.argv[1], sys.argv[2], unit_fn=unit_fn,
                       lease_s=float(sys.argv[3]), poll_s=0.05)
sys.exit(res.exit_code)
"""


def _fake_worker_argv(tmp_path, out, lease="2.0"):
    path = str(tmp_path / "fake_worker.py")
    if not os.path.exists(path):
        with open(path, "w") as f:
            f.write(_FAKE_WORKER.format(repo=REPO))
    return lambda wid: [sys.executable, path, out, wid, lease]


def _units(n, sleep=0.05):
    return [{"uid": f"u{i:02d}", "word": f"u{i:02d}", "sleep": sleep,
             "readout": {"layer": 1}} for i in range(n)]


def _fake_env(extra=None):
    env = {"TBX_OBS_PROGRESS_S": "0.1", "TBX_SUPERVISE_BACKOFF_S": "0"}
    env.update(extra or {})
    return env


def test_fleet_completes_and_merges(tmp_path):
    out = str(tmp_path / "fleet")
    units = _units(6)
    res = fleet.run_fleet(
        units, out, n_workers=2,
        worker_argv=_fake_worker_argv(tmp_path, out),
        worker_env=_fake_env(), lease_s=2.0, poll_s=0.1,
        supervise_poll=0.1, grace=1.0, wedge_after=30.0,
        max_incarnations=3, policy=FAST, spec_factor=0.0, max_wall_s=120.0)
    assert res.status == "done" and res.exit_code == 0
    assert res.committed == 6 and res.quarantined == 0
    sp = FleetSpool(os.path.join(out, "spool"))
    assert sorted(sp.done_uids()) == [f"u{i:02d}" for i in range(6)]
    # Merged stream green under the full gate (schema + fleet invariants).
    merged = os.path.join(out, "_events.jsonl")
    events = list(trace_report.iter_events(merged))
    assert trace_report.check(merged) == []
    assert trace_report.check_fleet(merged, events) == []
    assert os.path.exists(os.path.join(out, "_fleet.json"))


def test_fleet_drain_exits_75_and_resumes(tmp_path):
    out = str(tmp_path / "fleet")
    argv = _fake_worker_argv(tmp_path, out)
    # Slow units widen the drain window so some units stay pending.
    units = _units(8, sleep=0.4)
    timer = threading.Timer(1.2, supervise.request_drain)
    timer.start()
    try:
        res = fleet.run_fleet(
            units, out, n_workers=2, worker_argv=argv,
            worker_env=_fake_env(), lease_s=2.0, poll_s=0.1,
            supervise_poll=0.1, grace=2.0, wedge_after=30.0,
            max_incarnations=3, policy=FAST, spec_factor=0.0,
            max_wall_s=120.0)
    finally:
        timer.cancel()
        supervise.reset_drain()
    assert res.status == "drained"
    assert res.exit_code == supervise.EXIT_DRAINED
    sp = FleetSpool(os.path.join(out, "spool"))
    assert 0 < len(sp.done_uids()) < 8      # partial, at unit boundaries

    # Resume: the spool is durable — a fresh fleet finishes the rest.
    res2 = fleet.run_fleet(
        units, out, n_workers=2, worker_argv=argv,
        worker_env=_fake_env(), lease_s=2.0, poll_s=0.1,
        supervise_poll=0.1, grace=1.0, wedge_after=30.0,
        max_incarnations=3, policy=FAST, spec_factor=0.0, max_wall_s=120.0)
    assert res2.status == "done" and res2.exit_code == 0
    assert sorted(sp.done_uids()) == [f"u{i:02d}" for i in range(8)]


def test_fleet_speculation_rescues_straggler(tmp_path, monkeypatch):
    """One unit sleeps 30s (the straggler); the percentile deadline trips,
    a speculative copy goes to the other worker, the fleet finishes without
    waiting for the original, and the eventual losing commit is benign."""
    monkeypatch.setenv("TBX_FLEET_SPEC_MIN_S", "1")
    out = str(tmp_path / "fleet")
    units = _units(7, sleep=0.05)
    units[3]["sleep"] = 30.0                # first claimant wedges on it
    res = fleet.run_fleet(
        units, out, n_workers=2,
        worker_argv=_fake_worker_argv(tmp_path, out, lease="1.0"),
        worker_env=_fake_env(), lease_s=1.0, poll_s=0.1,
        supervise_poll=0.1, grace=1.0, wedge_after=60.0,
        max_incarnations=3, policy=FAST,
        spec_factor=2.0, spec_pct=75.0, max_wall_s=120.0)
    assert res.status == "done" and res.exit_code == 0
    assert res.committed == 7
    assert res.speculated >= 1
    sp = FleetSpool(os.path.join(out, "spool"))
    assert sorted(sp.done_uids()) == [f"u{i:02d}" for i in range(7)]
    # Exactly-once: one done file per unit regardless of the race; the
    # straggler's own commit (if it landed before the stop) parked in
    # duplicates/ rather than overwriting.
    with open(sp.done_path("u03")) as f:
        assert json.load(f)["uid"] == "u03"


# ---------------------------------------------------------------------------
# ISSUE 10 acceptance: the chaos e2e on real tiny-model workers.
# ---------------------------------------------------------------------------

def test_fleet_chaos_die_and_wedge_exactly_once(tmp_path):
    """3 synthetic tiny-model worker subprocesses over 12 words; worker w1
    is SIGKILL-equivalently killed mid-word (``die`` at its first commit)
    and worker w2 wedges mid-word (60s ``delay`` with a fresh heartbeat —
    the two-signal classifier's kill case).  The sweep must complete every
    word exactly once with zero ``.corrupt`` files, a green merged event
    stream, and the killed/wedged workers' units showing lease-expiry →
    re-issue chains in the merged ledger."""
    out = str(tmp_path / "fleet")
    words = [f"word{i:02d}" for i in range(12)]
    units = [{"uid": unit_id(w, {"layer": 1}), "word": w,
              "readout": {"layer": 1}} for w in words]
    plan = {"fleet.commit": [
        {"mode": "die", "times": 1, "match": "w1", "incarnation": 0},
        {"mode": "delay", "delay": 60.0, "times": 1, "match": "w2",
         "incarnation": 0},
    ]}
    env = _fake_env({"JAX_PLATFORMS": "cpu",
                     "TABOO_FAULT_PLAN": json.dumps(plan),
                     "TBX_OBS_PROGRESS_S": "0.2"})

    def argv(wid):
        return [sys.executable, "-m", "taboo_brittleness_tpu", "worker",
                "--fleet-dir", out, "--worker-id", wid]

    res = fleet.run_fleet(
        units, out, n_workers=3, worker_argv=argv, worker_env=env,
        spool_config={"mode": "synthetic", "words": words,
                      "max_new_tokens": 3},
        lease_s=3.0, poll_s=0.2, supervise_poll=0.2, grace=2.0,
        # Wedge threshold above the tiny-model compile (~10s of legitimate
        # event silence) but far below the 60s injected wedge.
        wedge_after=15.0, max_incarnations=4, spec_factor=0.0,
        policy=FAST, max_wall_s=500.0)

    assert res.status == "done", res.to_dict()
    assert res.exit_code == 0
    # Exactly-once: every word committed, once, and nothing quarantined.
    sp = FleetSpool(os.path.join(out, "spool"))
    assert sorted(sp.done_uids()) == sorted(u["uid"] for u in units)
    assert res.committed == 12 and res.quarantined == 0
    # Both chaos victims dropped a lease; both units were re-issued.
    assert res.lease_expiries >= 2, res.to_dict()
    assert res.reissued >= 2
    # Zero torn artifacts anywhere in the tree.
    corrupt = [os.path.join(r, n) for r, _, names in os.walk(str(tmp_path))
               for n in names if n.endswith(".corrupt")]
    assert corrupt == []
    # The killed worker burned an incarnation; so did the wedged one.
    incs = {w["worker_id"]: w["incarnations"] for w in res.workers}
    assert incs["w1"] >= 2 and incs["w2"] >= 2, incs

    # Merged event stream: green under the full trace_report gate
    # (schema + seq monotonicity + balanced spans + fleet invariants).
    merged = os.path.join(out, "_events.jsonl")
    events = list(trace_report.iter_events(merged))
    assert trace_report.check(merged) == []
    assert trace_report.check_fleet(merged, events) == []

    # The ledger records the lease-expiry → re-issue chain per victim.
    with open(os.path.join(out, "_failures.json")) as f:
        ledger = json.load(f)
    assert ledger["version"] == 3
    chains = ledger["fleet"]["reissues"]
    victims = {e["worker"] for chain in chains.values() for e in chain}
    assert {"w1", "w2"} <= victims, chains
    for chain in chains.values():
        for entry in chain:
            assert entry["reason"] == "lease-expired"
            assert entry["to_attempt"] == entry["from_attempt"] + 1

    # The wedged worker was killed by its supervisor for the two-signal
    # reason, not a timeout: its per-worker supervise record says wedged.
    with open(os.path.join(out, "_supervise.w2.json")) as f:
        sup = json.load(f)
    outcomes = [r["outcome"] for r in sup["incarnations"]]
    assert "wedged" in outcomes, outcomes
