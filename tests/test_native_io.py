"""Native parallel npz writer: byte-compatibility with np.load, fallback path."""

import os

import numpy as np
import pytest

from taboo_brittleness_tpu.runtime import native_io


def test_native_roundtrip_matches_numpy(tmp_path, rng):
    arrays = {
        "all_probs": rng.random((5, 7, 64)).astype(np.float32),
        "residual_stream_l2": rng.normal(size=(7, 16)).astype(np.float32),
        "ids": np.arange(13, dtype=np.int32),
        "flags": np.asarray([True, False, True]),
    }
    path = str(tmp_path / "pair.npz")
    used_native = native_io.save_npz(path, arrays)
    with np.load(path) as data:
        assert set(data.files) == set(arrays)
        for k, v in arrays.items():
            np.testing.assert_array_equal(data[k], v)
            assert data[k].dtype == v.dtype
    if not used_native:
        pytest.skip("native writer unavailable (no g++/zlib); numpy fallback verified")


@pytest.mark.skipif(not native_io.native_available(), reason="no native writer")
def test_native_multi_chunk_member(tmp_path, rng):
    """A member large enough to split across deflate chunks must still load."""
    big = rng.random((4 << 20,)).astype(np.float32)  # 16 MiB > 1 MiB chunk floor
    path = str(tmp_path / "big.npz")
    assert native_io.save_npz(path, {"big": big}, n_threads=4)
    with np.load(path) as data:
        np.testing.assert_array_equal(data["big"], big)


@pytest.mark.skipif(not native_io.native_available(), reason="no native writer")
def test_native_incompressible_member_drains_staging_buffer(tmp_path):
    """One thread + incompressible bytes > the 4 MiB staging buffer: the
    slice/drain loop (the >4 GiB-safety path of deflate_chunk) must produce a
    valid stream and CRC."""
    raw = np.frombuffer(np.random.default_rng(0).bytes(24 << 20), np.uint8)
    path = str(tmp_path / "incompressible.npz")
    assert native_io.save_npz(path, {"raw": raw}, n_threads=1)
    with np.load(path) as data:
        np.testing.assert_array_equal(data["raw"], raw)


@pytest.mark.skipif(not native_io.native_available(), reason="no native writer")
def test_native_empty_and_noncontiguous(tmp_path):
    path = str(tmp_path / "odd.npz")
    base = np.arange(64, dtype=np.float32).reshape(8, 8)
    arrays = {"strided": base[:, ::2], "empty": np.zeros((0, 3), np.float32)}
    assert native_io.save_npz(path, arrays)
    with np.load(path) as data:
        np.testing.assert_array_equal(data["strided"], base[:, ::2])
        assert data["empty"].shape == (0, 3)


def test_cache_save_pair_uses_writer(tmp_path, rng):
    """save_pair/save_summary keep working through the native path."""
    from taboo_brittleness_tpu.runtime import cache as cache_io

    npz, js = cache_io.pair_paths(str(tmp_path), "moon", 0, mkdir=True)
    probs = rng.random((3, 4, 11)).astype(np.float32)
    resid = rng.normal(size=(4, 8)).astype(np.float32)
    cache_io.save_pair(npz, js, probs, ["<bos>", "a", "b", "c"], "resp", "prompt",
                       residual_stream=resid, layer_idx=2)
    pair = cache_io.load_pair(npz, js, layer_idx=2)
    np.testing.assert_array_equal(pair.all_probs, probs)
    np.testing.assert_array_equal(pair.residual_stream, resid)
