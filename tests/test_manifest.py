"""Run manifest: timed stages, environment capture, save/load."""

import json

import pytest

from taboo_brittleness_tpu.runtime.manifest import RunManifest, maybe_profile


def test_manifest_records_stages_and_saves(tmp_path):
    m = RunManifest(command="test", config={"a": 1})
    with m.stage("work", word="ship"):
        pass
    with pytest.raises(RuntimeError):
        with m.stage("boom"):
            raise RuntimeError("x")
    m.add_artifact("results/foo.json")
    m.extra["note"] = "hi"

    path = m.save(str(tmp_path / "run_manifest.json"))
    with open(path) as f:
        data = json.load(f)
    assert data["command"] == "test"
    assert data["config"] == {"a": 1}
    assert [s["name"] for s in data["stages"]] == ["work", "boom"]
    assert data["stages"][0]["status"] == "ok"
    assert data["stages"][0]["word"] == "ship"
    assert data["stages"][1]["status"] == "error"
    assert all(s["seconds"] >= 0 for s in data["stages"])
    assert data["artifacts"] == ["results/foo.json"]
    assert data["extra"]["note"] == "hi"
    assert "backend" in data["environment"] or "jax_error" in data["environment"]


def test_maybe_profile_noop_without_dir():
    with maybe_profile(None):
        x = 1
    assert x == 1


def test_maybe_profile_writes_trace(tmp_path):
    import os

    import jax
    import jax.numpy as jnp

    trace_dir = str(tmp_path / "trace")
    with maybe_profile(trace_dir):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    # jax writes a plugins/profile subtree with at least one file
    found = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs]
    assert found, "profiler trace produced no files"
