"""Mesh/sharding + ring attention on the virtual 8-device CPU mesh
(SURVEY.md §4 test plan item 4): sharded results must equal single-device."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from taboo_brittleness_tpu.config import MeshConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.parallel import mesh as meshlib
from taboo_brittleness_tpu.parallel import ring

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def test_make_mesh_fills_free_axis():
    m = meshlib.make_mesh(MeshConfig(dp=-1, tp=2, sp=1))
    assert m.shape == {"dp": 4, "tp": 2, "sp": 1}
    m2 = meshlib.make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert m2.shape == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        meshlib.make_mesh(MeshConfig(dp=3, tp=2, sp=1))


def test_shard_params_and_forward_match_single_device():
    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=200)  # 200 % tp==0
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 200, size=(4, 6)))

    ref = gemma2.forward(params, cfg, ids).logits

    m = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sharded_params = meshlib.shard_params(params, cfg, m)
    sharded_ids = meshlib.shard_batch(ids, m)
    out = jax.jit(lambda p, i: gemma2.forward(p, cfg, i).logits)(
        sharded_params, sharded_ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-5)


def test_tp_topk_matches_global_topk():
    m = meshlib.make_mesh(MeshConfig(dp=1, tp=8, sp=1))
    V, k = 64, 5
    vals = jnp.asarray(np.random.default_rng(1).normal(size=(3, V)), jnp.float32)

    def f(v):
        return meshlib.tp_topk(v, k, axis_name="tp", shard_size=V // 8)

    got_v, got_i = meshlib.shard_map(
        f, m, in_specs=(P(None, "tp"),), out_specs=P(None, None),
    )(vals)
    exp_v, exp_i = lax.top_k(vals, k)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(exp_v), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(exp_i))


def test_tp_lens_forward_matches_single_device_without_regather():
    """The tp lens path (vocab-sharded unembed + tp_topk merge) must equal the
    single-device readout AND never materialize a full-vocab [*, T, V] tensor
    (VERDICT round-1 item 4; SURVEY.md §2.3 'vocab-sharded unembed')."""
    from taboo_brittleness_tpu.ops import lens

    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=200)
    params = gemma2.init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(4)
    B, T, k = 4, 6, 3
    ids = jnp.asarray(rng.integers(0, 200, size=(B, T)))
    targets = jnp.asarray(rng.integers(0, 200, size=(B,)), jnp.int32)

    ref = lens.lens_forward(params, cfg, ids, targets, tap_layer=2, top_k=k,
                            use_pallas=False)

    m = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sp = meshlib.shard_params(params, cfg, m)
    sids = meshlib.shard_batch(ids, m)
    stgt = meshlib.shard_batch(targets, m)

    step = jax.jit(lambda p, i, t: lens.lens_forward(
        p, cfg, i, t, tap_layer=2, top_k=k, tp_mesh=m))
    got = step(sp, sids, stgt)

    np.testing.assert_allclose(np.asarray(got.tap.target_prob),
                               np.asarray(ref.tap.target_prob),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(got.tap.topk_ids),
                                  np.asarray(ref.tap.topk_ids))
    np.testing.assert_allclose(np.asarray(got.tap.topk_probs),
                               np.asarray(ref.tap.topk_probs),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got.residual),
                               np.asarray(ref.residual), atol=2e-5, rtol=1e-4)

    # No replicated or per-dp-shard full-vocab probability/logit tensor: the
    # compiled program must only ever hold [*, T, V/tp] blocks.
    hlo = step.lower(sp, sids, stgt).compile().as_text()
    for shape in (f"{B},{T},200", f"{B // 2},{T},200"):
        assert f"f32[{shape}]" not in hlo, f"full-vocab tensor f32[{shape}] found"


def test_tp_aggregate_from_residual_matches_single_device():
    from taboo_brittleness_tpu.ops import lens

    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=200)
    params = gemma2.init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.default_rng(5)
    B, T, k = 4, 6, 4
    resid = jnp.asarray(rng.normal(size=(B, T, cfg.hidden_size)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 200, size=(B, T)))
    mask = jnp.asarray(rng.random((B, T)) > 0.3)

    exp_ids, exp_vals = lens.aggregate_from_residual(
        params, cfg, resid, ids, mask, top_k=k)

    m = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sp = meshlib.shard_params(params, cfg, m)
    got_ids, got_vals = lens.aggregate_from_residual_tp(
        sp, cfg, meshlib.shard_batch(resid, m), meshlib.shard_batch(ids, m),
        meshlib.shard_batch(mask, m), top_k=k, mesh=m)
    np.testing.assert_array_equal(np.asarray(got_ids), np.asarray(exp_ids))
    np.testing.assert_allclose(np.asarray(got_vals), np.asarray(exp_vals),
                               atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("spike_masked", [False, True])
def test_tp_decode_with_arm_edits_matches_single_device(spike_masked):
    """EXECUTED value parity for the 9B chain's last link: tp=4 (x dp=2)
    ``greedy_decode`` with per-row arm edit_params and in-flight residual
    capture must produce the single-device tokens, lengths and residuals —
    previously tp decode was only compile-proven (AOT .lower at 9B shapes)
    and smoke-run without assertions in the dryrun."""
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import decode

    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=200)
    params = gemma2.init_params(jax.random.PRNGKey(4), cfg)
    sae = sae_ops.init_random(jax.random.PRNGKey(5), cfg.hidden_size, 32)
    rng = np.random.default_rng(6)
    B, tap = 4, 2
    prompts = [list(rng.integers(1, 200, size=n)) for n in (5, 7, 6, 7)]
    padded, valid, positions = decode.pad_prompts(prompts)

    ep = {"sae": sae, "layer": tap,
          "latent_ids": jnp.asarray(                    # a different arm per row
              rng.integers(0, 32, size=(B, 3)), jnp.int32)}
    if spike_masked:
        ep["spike_positions"] = jnp.asarray(
            rng.integers(0, 8, size=(B, 2)), jnp.int32)

    def run(p, ids, val, pos, ep_):
        return decode.greedy_decode(
            p, cfg, ids, val, pos, max_new_tokens=4,
            edit_fn=iv.sae_ablation_edit, edit_params=ep_, stop_ids=(-1,),
            capture_residual_layer=tap)

    base = run(params, jnp.asarray(padded), jnp.asarray(valid),
               jnp.asarray(positions), ep)

    m = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sp = meshlib.shard_params(params, cfg, m)
    ep_sharded = {**ep, "latent_ids": meshlib.shard_batch(ep["latent_ids"], m)}
    if spike_masked:
        ep_sharded["spike_positions"] = meshlib.shard_batch(
            ep["spike_positions"], m)
    got = run(sp, meshlib.shard_batch(jnp.asarray(padded), m),
              meshlib.shard_batch(jnp.asarray(valid), m),
              meshlib.shard_batch(jnp.asarray(positions), m), ep_sharded)

    np.testing.assert_array_equal(np.asarray(got.tokens),
                                  np.asarray(base.tokens))
    np.testing.assert_array_equal(np.asarray(got.lengths),
                                  np.asarray(base.lengths))
    np.testing.assert_allclose(np.asarray(got.residual),
                               np.asarray(base.residual),
                               atol=2e-5, rtol=1e-4)


def test_analyze_word_on_device_tp_mesh_odd_batch():
    """Pipeline-level tp path with a batch that does NOT divide dp: rows are
    padded for the shard_map and stripped from the outputs."""
    from taboo_brittleness_tpu.pipelines import logit_lens
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=200)
    params = gemma2.init_params(jax.random.PRNGKey(3), cfg)
    tok = WordTokenizer(["moon", "hint", "Give", "me", "a", "more"],
                        vocab_size=200)
    prompts = ["Give me a hint", "a hint", "more hint"]   # B=3, dp=2

    base = logit_lens.analyze_word_on_device(
        params, cfg, tok, "moon", prompts, layer_idx=2, top_k=3,
        max_new_tokens=4)

    m = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sp = meshlib.shard_params(params, cfg, m)
    got = logit_lens.analyze_word_on_device(
        sp, cfg, tok, "moon", prompts, layer_idx=2, top_k=3,
        max_new_tokens=4, mesh=m)

    assert got.guess_ids == base.guess_ids
    assert got.response_texts == base.response_texts
    for a, b in zip(got.target_probs, base.target_probs):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=1e-4)


def test_9b_placement_math_fits_v5e_hbm():
    """SURVEY.md §7 hard part #2: bf16 9B params don't fit one 16 GB chip
    replicated; the tp param policy makes them fit at tp>=2."""
    cfg9 = gemma2.PRESETS["gemma2_9b"]
    shapes = jax.eval_shape(
        lambda key: gemma2.init_params(key, cfg9), jax.random.PRNGKey(0))
    total = meshlib.per_device_bytes(shapes)
    assert total > 16 * 1024**3          # replicated: does NOT fit
    specs = meshlib.param_specs(cfg9)
    for tp in (2, 4):
        m = meshlib.make_mesh(MeshConfig(dp=-1, tp=tp, sp=1))
        per_dev = meshlib.per_device_bytes(shapes, specs, m)
        assert per_dev < 16 * 1024**3, (tp, per_dev)
        # Sharded axes actually divide: the policy halves the big matrices.
        assert per_dev < total / tp * 1.2


@pytest.mark.parametrize("sliding_window", [None, 5])
def test_ring_attention_matches_single_device(sliding_window):
    rng = np.random.default_rng(2)
    B, T, H, K, Dh, SP = 2, 16, 4, 2, 8, 4
    q = jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    valid = jnp.ones((B, T), bool)

    mask = gemma2.causal_mask(positions, positions, valid, sliding_window)
    expected = gemma2.attend(q, k, v, mask, scaling=0.25, logit_cap=50.0)

    m = meshlib.make_mesh(MeshConfig(dp=1, tp=2, sp=4))

    def f(q, k, v, pos, val):
        return ring.ring_attention(
            q, k, v, pos, pos, val, axis_name="sp",
            scaling=0.25, logit_cap=50.0, sliding_window=sliding_window)

    got = meshlib.shard_map(
        f, m,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"),
                  P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(q, k, v, positions, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=1e-4)


def test_forward_sp_matches_dense_forward_beyond_sliding_window():
    """Full-model sequence-parallel path (parallel/sp.py): seq=16 exceeds the
    tiny config's sliding_window=3, so sliding AND global layers both cross
    sp-shard boundaries — results must equal the dense single-device forward
    (VERDICT round-1 item 8)."""
    from taboo_brittleness_tpu.parallel import sp as splib

    cfg = gemma2.PRESETS["gemma2_tiny"]
    assert cfg.sliding_window < 16
    params = gemma2.init_params(jax.random.PRNGKey(6), cfg)
    rng = np.random.default_rng(7)
    B, T = 2, 16
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(B, T)))

    dense = gemma2.forward(params, cfg, ids, per_layer_fn=lambda h, i: h)

    m = meshlib.make_mesh(MeshConfig(dp=-1, tp=1, sp=2))
    got = splib.forward_sp(params, cfg, ids, m, tap_layer=2)

    np.testing.assert_allclose(np.asarray(got.logits),
                               np.asarray(dense.logits),
                               atol=3e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got.last_hidden),
                               np.asarray(dense.last_hidden),
                               atol=3e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got.residual),
                               np.asarray(dense.taps[2]),
                               atol=3e-5, rtol=1e-4)


def test_forward_sp_with_left_padding():
    from taboo_brittleness_tpu.parallel import sp as splib
    from taboo_brittleness_tpu.runtime import decode as decode_mod

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(8), cfg)
    rng = np.random.default_rng(9)
    prompts = [list(rng.integers(1, cfg.vocab_size, size=n)) for n in (12, 16)]
    padded, valid, positions = decode_mod.pad_prompts(prompts)

    dense = gemma2.forward(
        params, cfg, jnp.asarray(padded), positions=jnp.asarray(positions),
        attn_validity=jnp.asarray(valid, bool))

    m = meshlib.make_mesh(MeshConfig(dp=-1, tp=1, sp=4))
    got = splib.forward_sp(
        params, cfg, jnp.asarray(padded), m,
        positions=jnp.asarray(positions),
        attn_validity=jnp.asarray(valid, bool))

    # Compare only valid columns (pad rows see garbage masks either way).
    va = np.asarray(valid)
    np.testing.assert_allclose(np.asarray(got.logits)[va],
                               np.asarray(dense.logits)[va],
                               atol=3e-5, rtol=1e-4)


def test_ring_attention_with_padding():
    rng = np.random.default_rng(3)
    B, T, H, K, Dh = 1, 8, 2, 1, 4
    q = jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    valid = jnp.asarray([[False, False, True, True, True, True, True, True]])
    positions = jnp.asarray([[0, 0, 0, 1, 2, 3, 4, 5]])

    mask = gemma2.causal_mask(positions, positions, valid)
    expected = gemma2.attend(q, k, v, mask, scaling=0.5, logit_cap=30.0)

    m = meshlib.make_mesh(MeshConfig(dp=1, tp=1, sp=8))

    def f(q, k, v, pos, val):
        return ring.ring_attention(q, k, v, pos, pos, val, axis_name="sp",
                                   scaling=0.5, logit_cap=30.0)

    got = meshlib.shard_map(
        f, m,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"),
                  P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(q, k, v, positions, valid)
    got_np = np.asarray(got)[:, 2:]
    np.testing.assert_allclose(got_np, np.asarray(expected)[:, 2:],
                               atol=2e-5, rtol=1e-4)


# ---------------------------------------------------------------------------
# Round-3: the sp axis is a product path (VERDICT round-2 item 6) — full
# LensTap stats under ring attention, reachable from lens_forward via mesh.
# ---------------------------------------------------------------------------

def test_lens_forward_sp_matches_dense_lens():
    """Per-layer lens stats computed shard-locally under dp x sp must equal
    the dense path — including a T NOT divisible by sp (right padding)."""
    from taboo_brittleness_tpu.ops import lens as lens_ops
    from taboo_brittleness_tpu.parallel import sp as splib

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(6), cfg)
    rng = np.random.default_rng(11)
    B, T = 4, 15                       # 15 % 4 != 0 -> pads to 16
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(B, T)))
    targets = jnp.asarray([3, 5, 7, 9], jnp.int32)

    dense = lens_ops.lens_forward(params, cfg, ids, targets,
                                  tap_layer=2, top_k=3)
    m = meshlib.make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    got = splib.lens_forward_sp(params, cfg, ids, targets, m,
                                tap_layer=2, top_k=3)

    np.testing.assert_allclose(np.asarray(got.tap.target_prob),
                               np.asarray(dense.tap.target_prob),
                               atol=3e-5, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(got.tap.argmax_id),
                                  np.asarray(dense.tap.argmax_id))
    np.testing.assert_allclose(np.asarray(got.tap.topk_probs),
                               np.asarray(dense.tap.topk_probs),
                               atol=3e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got.residual),
                               np.asarray(dense.residual),
                               atol=3e-5, rtol=1e-4)
    assert got.tap.target_prob.shape == dense.tap.target_prob.shape


def test_lens_forward_routes_through_sp_mesh():
    """ops.lens.lens_forward with an sp>1 (tp=1) mesh takes the ring path and
    agrees with the dense result — the config-selected switch pipelines use."""
    from taboo_brittleness_tpu.ops import lens as lens_ops
    from taboo_brittleness_tpu.runtime import decode as decode_mod

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(12), cfg)
    rng = np.random.default_rng(13)
    prompts = [list(rng.integers(1, cfg.vocab_size, size=n)) for n in (10, 14)]
    padded, valid, positions = decode_mod.pad_prompts(prompts)
    args = (jnp.asarray(padded), jnp.asarray([2, 2], jnp.int32))
    kw = dict(tap_layer=2, top_k=3, positions=jnp.asarray(positions),
              attn_validity=jnp.asarray(valid, bool))

    dense = lens_ops.lens_forward(params, cfg, *args, **kw)
    m = meshlib.make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    got = lens_ops.lens_forward(params, cfg, *args, **kw, tp_mesh=m)

    va = np.asarray(valid)
    np.testing.assert_allclose(
        np.asarray(got.tap.target_prob)[:, va],    # [L, B, T] -> [L, nnz]
        np.asarray(dense.tap.target_prob)[:, va],
        atol=3e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got.residual)[va],
                               np.asarray(dense.residual)[va],
                               atol=3e-5, rtol=1e-4)


def test_analyze_word_on_device_sp_mesh_matches_dense():
    """Pipeline-level: the LL evaluation produces identical guesses whether
    the lens pass runs dense or sequence-parallel (sp now serves the lens
    workload end-to-end instead of being a tested-but-unreachable exhibit)."""
    from taboo_brittleness_tpu.pipelines import logit_lens
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(14), cfg)
    tok = WordTokenizer(["moon", "hint", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)

    kw = dict(layer_idx=2, top_k=3, max_new_tokens=5)
    dense = logit_lens.analyze_word_on_device(
        params, cfg, tok, "moon", ["Give me a hint", "a hint"], **kw)
    m = meshlib.make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    sp = logit_lens.analyze_word_on_device(
        params, cfg, tok, "moon", ["Give me a hint", "a hint"], mesh=m, **kw)

    assert sp.guesses == dense.guesses
    assert sp.guess_ids == dense.guess_ids
    for a, b in zip(sp.target_probs, dense.target_probs):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=1e-4)


def test_sp_lens_route_rejects_unsupported_flags():
    """The sp branch cannot honor compute_logits or a forced Pallas kernel —
    it must fail loudly instead of silently returning logits=None / falling
    back (review finding, round 3)."""
    from taboo_brittleness_tpu.ops import lens as lens_ops

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(30), cfg)
    ids = jnp.ones((2, 8), jnp.int32)
    targets = jnp.zeros((2,), jnp.int32)
    m = meshlib.make_mesh(MeshConfig(dp=2, tp=1, sp=4))

    with pytest.raises(ValueError, match="sp lens path"):
        lens_ops.lens_forward(params, cfg, ids, targets, tap_layer=2,
                              compute_logits=True, tp_mesh=m)
    with pytest.raises(ValueError, match="Pallas"):
        lens_ops.lens_forward(params, cfg, ids, targets, tap_layer=2,
                              use_pallas=True, tp_mesh=m)


# ---------------------------------------------------------------------------
# Multi-host glue (parallel/multihost.py).  Virtual CPU devices all share
# process_index 0, so the host-grouping branch is exercised by spoofing the
# index; the single-process paths run for real.
# ---------------------------------------------------------------------------

def test_multihost_initialize_is_noop_single_process(monkeypatch):
    from taboo_brittleness_tpu.parallel import multihost

    for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_ID"):
        monkeypatch.delenv(v, raising=False)
    assert multihost.initialize() is False    # no cluster env -> no-op


def test_multihost_mesh_single_process_matches_make_mesh():
    from taboo_brittleness_tpu.parallel import multihost

    m = multihost.make_host_mesh(MeshConfig(dp=2, tp=4, sp=1))
    assert dict(m.shape) == {"dp": 2, "tp": 4, "sp": 1}


def test_multihost_mesh_keeps_model_axes_on_host():
    """With devices spoofed onto 2 hosts, every (tp, sp) column of the mesh
    must sit on ONE host — the model axes ride ICI, dp crosses DCN."""
    from taboo_brittleness_tpu.parallel import multihost

    class Dev:
        def __init__(self, i, host):
            self.id = i
            self.process_index = host

        def __repr__(self):
            return f"Dev({self.id},h{self.process_index})"

    devs = [Dev(i, i // 4) for i in range(8)]      # 2 hosts x 4 devices
    m = multihost.make_host_mesh(MeshConfig(dp=2, tp=4, sp=1), devices=devs)
    arr = np.asarray(m.devices)
    assert arr.shape == (2, 4, 1)
    for d in range(2):                              # each dp row = one host
        hosts = {arr[d, t, 0].process_index for t in range(4)}
        assert len(hosts) == 1

    with pytest.raises(ValueError, match="must divide"):
        multihost.make_host_mesh(MeshConfig(dp=1, tp=8, sp=1), devices=devs)

    # -1 model axes absorb the PER-HOST remainder (tp=4 here), never another
    # host's devices; uneven hosts are rejected outright.
    m2 = multihost.make_host_mesh(MeshConfig(dp=-1, tp=-1, sp=1), devices=devs)
    assert dict(m2.shape) == {"dp": 2, "tp": 4, "sp": 1}
    with pytest.raises(ValueError, match="uneven"):
        multihost.make_host_mesh(MeshConfig(dp=-1, tp=1, sp=1),
                                 devices=devs[:7])


def test_forward_sp_long_context_sp8():
    """Long-context scale check for the ring path: T=256 over sp=8 (32
    positions per shard, ~85x the tiny config's sliding window) — ring
    attention must still match the dense forward bit-for-tolerance.  The
    smaller sp tests catch boundary logic; this one catches accumulation
    drift and window handling across MANY shard hops."""
    from taboo_brittleness_tpu.parallel import sp as splib

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(8), cfg)
    rng = np.random.default_rng(9)
    B, T = 1, 256
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(B, T)))

    dense = gemma2.forward(params, cfg, ids, per_layer_fn=lambda h, i: h)

    m = meshlib.make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    got = splib.forward_sp(params, cfg, ids, m, tap_layer=2)

    np.testing.assert_allclose(np.asarray(got.logits),
                               np.asarray(dense.logits),
                               atol=5e-5, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(got.residual),
                               np.asarray(dense.taps[2]),
                               atol=5e-5, rtol=2e-4)
