"""Mesh/sharding + ring attention on the virtual 8-device CPU mesh
(SURVEY.md §4 test plan item 4): sharded results must equal single-device."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from taboo_brittleness_tpu.config import MeshConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.parallel import mesh as meshlib
from taboo_brittleness_tpu.parallel import ring

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def test_make_mesh_fills_free_axis():
    m = meshlib.make_mesh(MeshConfig(dp=-1, tp=2, sp=1))
    assert m.shape == {"dp": 4, "tp": 2, "sp": 1}
    m2 = meshlib.make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert m2.shape == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        meshlib.make_mesh(MeshConfig(dp=3, tp=2, sp=1))


def test_shard_params_and_forward_match_single_device():
    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=200)  # 200 % tp==0
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 200, size=(4, 6)))

    ref = gemma2.forward(params, cfg, ids).logits

    m = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sharded_params = meshlib.shard_params(params, cfg, m)
    sharded_ids = meshlib.shard_batch(ids, m)
    out = jax.jit(lambda p, i: gemma2.forward(p, cfg, i).logits)(
        sharded_params, sharded_ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-5)


def test_tp_topk_matches_global_topk():
    m = meshlib.make_mesh(MeshConfig(dp=1, tp=8, sp=1))
    V, k = 64, 5
    vals = jnp.asarray(np.random.default_rng(1).normal(size=(3, V)), jnp.float32)

    def f(v):
        return meshlib.tp_topk(v, k, axis_name="tp", shard_size=V // 8)

    got_v, got_i = meshlib.shard_map(
        f, m, in_specs=(P(None, "tp"),), out_specs=P(None, None),
    )(vals)
    exp_v, exp_i = lax.top_k(vals, k)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(exp_v), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(exp_i))


@pytest.mark.parametrize("sliding_window", [None, 5])
def test_ring_attention_matches_single_device(sliding_window):
    rng = np.random.default_rng(2)
    B, T, H, K, Dh, SP = 2, 16, 4, 2, 8, 4
    q = jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    valid = jnp.ones((B, T), bool)

    mask = gemma2.causal_mask(positions, positions, valid, sliding_window)
    expected = gemma2.attend(q, k, v, mask, scaling=0.25, logit_cap=50.0)

    m = meshlib.make_mesh(MeshConfig(dp=1, tp=2, sp=4))

    def f(q, k, v, pos, val):
        return ring.ring_attention(
            q, k, v, pos, pos, val, axis_name="sp",
            scaling=0.25, logit_cap=50.0, sliding_window=sliding_window)

    got = meshlib.shard_map(
        f, m,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"),
                  P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(q, k, v, positions, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=1e-4)


def test_ring_attention_with_padding():
    rng = np.random.default_rng(3)
    B, T, H, K, Dh = 1, 8, 2, 1, 4
    q = jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, Dh)), jnp.float32)
    valid = jnp.asarray([[False, False, True, True, True, True, True, True]])
    positions = jnp.asarray([[0, 0, 0, 1, 2, 3, 4, 5]])

    mask = gemma2.causal_mask(positions, positions, valid)
    expected = gemma2.attend(q, k, v, mask, scaling=0.5, logit_cap=30.0)

    m = meshlib.make_mesh(MeshConfig(dp=1, tp=1, sp=8))

    def f(q, k, v, pos, val):
        return ring.ring_attention(q, k, v, pos, pos, val, axis_name="sp",
                                   scaling=0.5, logit_cap=30.0)

    got = meshlib.shard_map(
        f, m,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"),
                  P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(q, k, v, positions, valid)
    got_np = np.asarray(got)[:, 2:]
    np.testing.assert_allclose(got_np, np.asarray(expected)[:, 2:],
                               atol=2e-5, rtol=1e-4)
