"""In-serve speculation (serve/spec_engine.py, ISSUE 13).

The lossless contract, gated: behind ``TBX_SERVE_SPECULATE=1`` the
speculative engine's token streams are ``array_equal`` to the vanilla
``serve.step`` engine across every scenario, mixed words, ragged slot
lengths, EOS/budget early stop, slot recycle mid-block and drain
mid-block.  Plus the satellites' seams:

- zero AOT misses after ``warm_start`` for BOTH spec programs;
- the adaptive-depth scenario's early-exit accounting (opt-in, excluded
  from exactness by contract);
- the ``serve.spec.verify`` fault site: transient retry-in-place,
  permanent single-session quarantine (batch lives), env fault plan;
- per-word (k, G) plan resolution at admission (env > calibration
  artifact > heuristic);
- the calibrator's batch-width cost term (optimal G grows with occupancy);
- the bench_compare / trace_report / loadgen reporting surfaces.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

from taboo_brittleness_tpu import obs
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.ops import sae as sae_ops
from taboo_brittleness_tpu.perf import spec_calibrate
from taboo_brittleness_tpu.runtime import aot, chat, resilience, speculate, supervise
from taboo_brittleness_tpu.runtime.resilience import FaultInjector
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer, target_token_id
from taboo_brittleness_tpu.serve import loadgen, spec_engine
from taboo_brittleness_tpu.serve.engine import EngineConfig, ServeEngine
from taboo_brittleness_tpu.serve.scheduler import (
    Request, SlotScheduler, default_scenarios)
from taboo_brittleness_tpu.serve.spec_engine import SpecServeEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402
import trace_report  # noqa: E402

WORDS = ["ship", "moon", "hint", "clue", "secret", "word", "is", "My",
         "Give", "me", "a", "the", "about"]
TAP = 2

#: scenarios under the lossless contract (adaptive_depth is excluded BY
#: contract — it trades exactness for the depth-k early exit).
LOSSLESS = ("chat", "chat_lens", "sae_ablate", "projection", "forcing")


@pytest.fixture(scope="module")
def tiny():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(7), cfg)
    tok = WordTokenizer(WORDS, vocab_size=cfg.vocab_size)
    sae = sae_ops.init_random(jax.random.PRNGKey(8), cfg.hidden_size, 64)
    return params, cfg, tok, sae


@pytest.fixture(autouse=True)
def _clean_state():
    supervise.reset_drain()
    resilience.set_injector(FaultInjector())
    yield
    supervise.reset_drain()
    resilience.set_injector(FaultInjector())


def make_engine(tiny, cls, *, slots=3, stop_ids=(-1,), max_context=48,
                **kw):
    """Either engine class over the same envelope; stop_ids=(-1,) =
    fixed-length sessions (uniform work, column-by-column comparison)."""
    params, cfg, tok, sae = tiny
    return cls(
        params, cfg, tok,
        engine_config=EngineConfig(
            slots=slots, max_context=max_context, prompt_cols=24,
            latent_slots=4, proj_rank=2,
            sae_layer=TAP, proj_layer=TAP, tap_layer=TAP,
            stop_ids=stop_ids),
        sae=sae, **kw)


def run_sched(engine, tok, specs, *, scenarios=None, max_new=5,
              step_hook=None):
    """Drive ``specs`` = [(scenario_name, prompt), ...] through a fresh
    scheduler; returns ({request_id: Response}, scheduler).  Requests are
    rebuilt each call (ids/seeds deterministic) so both arms see identical
    inputs."""
    scenarios = scenarios or default_scenarios(max_new_tokens=max_new)
    done = {}
    sched = SlotScheduler(engine,
                          lens_target_id=target_token_id(tok, "ship"),
                          on_complete=lambda r: done.__setitem__(r.id, r))
    for i, (name, prompt) in enumerate(specs):
        assert sched.submit(Request(id=f"r{i:03d}-{name}", prompt=prompt,
                                    scenario=scenarios[name], seed=100 + i))
    if step_hook is not None:
        step_hook(sched)
    sched.run_until_idle()
    return done, sched


def assert_streams_equal(off, on, *, lens_atol=1e-4):
    """Token streams bit-identical (the contract); lens probs allclose
    (chunk-shaped f32 fusions may reassociate — PR 8/9 precedent)."""
    assert set(off) == set(on)
    for rid in sorted(off):
        a, b = off[rid], on[rid]
        assert b.tokens == a.tokens, (
            f"{rid}: spec-on stream diverged\n off={a.tokens}\n on={b.tokens}")
        assert b.finish == a.finish, (rid, a.finish, b.finish)
        if a.lens_probs is not None:
            assert b.lens_probs is not None and np.allclose(
                a.lens_probs, b.lens_probs, atol=lens_atol), rid


# ---------------------------------------------------------------------------
# The lossless contract.
# ---------------------------------------------------------------------------

def test_lossless_all_scenarios(tiny):
    """Every lossless scenario through both arms — token streams exactly
    equal, and the speculative arm actually speculated (accepted > 0)."""
    _, _, tok, _ = tiny
    specs = [(name, "Give me a hint about the word") for name in LOSSLESS]
    off, _ = run_sched(make_engine(tiny, ServeEngine), tok, specs)
    eng = make_engine(tiny, SpecServeEngine)
    on, _ = run_sched(eng, tok, specs)
    assert_streams_equal(off, on)
    stats = eng.accept_stats()
    assert stats["drafted"] > 0 and stats["accepted"] > 0
    assert 0.0 < stats["accept_rate"] <= 1.0
    # Multi-token blocks resolved in fewer verify launches than tokens
    # emitted by the vanilla engine's one-per-step cadence.
    assert stats["tokens_per_verify"] > 0


def test_lossless_ragged_prompts_and_recycle(tiny):
    """Ragged slot lengths + recycle mid-block: more requests than slots,
    prompts of very different lengths, fixed-length sessions — streams
    stay bit-identical through slot reuse."""
    _, _, tok, _ = tiny
    specs = [
        ("chat", "hint"),
        ("chat_lens", "Give me a clue about the word"),
        ("sae_ablate", "My secret word is a ship about the moon"),
        ("chat", "Give me a hint"),
        ("projection", "a clue about a clue about a clue"),
        ("forcing", "me"),
        ("chat", "the secret is the word"),
    ]
    off, _ = run_sched(make_engine(tiny, ServeEngine, slots=2), tok, specs)
    on, sched = run_sched(make_engine(tiny, SpecServeEngine, slots=2),
                          tok, specs)
    assert_streams_equal(off, on)
    assert sched.completed == len(specs) and sched.quarantined == 0


def test_lossless_eos_and_budget_early_stop(tiny):
    """Real stop ids: sessions end on EOS/end-of-turn inside a block or on
    budget — the finish reason and the (possibly short) stream both match
    the vanilla arm."""
    _, _, tok, _ = tiny
    stop = (chat.EOS_ID, chat.END_OF_TURN_ID)
    specs = [("chat", "Give me a hint"), ("forcing", "Give me a hint"),
             ("chat_lens", "clue me"), ("chat", "word is moon")]
    off, _ = run_sched(make_engine(tiny, ServeEngine, stop_ids=stop),
                       tok, specs, max_new=8)
    on, _ = run_sched(make_engine(tiny, SpecServeEngine, stop_ids=stop),
                      tok, specs, max_new=8)
    assert_streams_equal(off, on)
    assert {r.finish for r in off.values()} <= {"eos", "budget"}


def test_lossless_drain_mid_block(tiny):
    """drain() between verify launches: in-flight sessions run to
    completion (zero drops), new submits are rejected, streams unchanged."""
    _, _, tok, _ = tiny
    specs = [("chat", "Give me a hint"), ("chat_lens", "a clue"),
             ("sae_ablate", "the word is")]
    off, _ = run_sched(make_engine(tiny, ServeEngine), tok, specs)

    def hook(sched):
        sched.step()                   # one verify block in flight
        sched.drain()
        rejected = sched.submit(Request(
            id="r999-late", prompt="hint",
            scenario=default_scenarios(max_new_tokens=5)["chat"], seed=9))
        assert rejected is False

    on, sched = run_sched(make_engine(tiny, SpecServeEngine), tok, specs,
                          step_hook=hook)
    assert_streams_equal(off, on)
    assert sched.completed == len(specs) and sched.rejected == 1


def test_lossless_multi_word_engine(tiny):
    """Mixed words through the delta-bank spec engine: the seeded loadgen
    schedule (words, scenarios, prompts) replayed over both arms — every
    lossless stream identical; the off arm's report has no spec block,
    the on arm's does."""
    del tiny  # the synthetic builders own their params

    def arm(speculative):
        engine, scenarios, lens_tgt = loadgen.build_synthetic_multi_engine(
            words=("ship", "moon"), slots=3, max_new_tokens=5,
            speculative=speculative)
        streams = {}
        report = loadgen.run_inprocess(
            engine, n_requests=10, seed=11, rate=500.0, concurrency=6,
            scenarios=scenarios, lens_target_id=lens_tgt,
            words=("ship", "moon"),
            on_complete=lambda r: streams.__setitem__(
                r.id, (r.scenario, r.word, tuple(r.tokens))))
        return streams, report

    streams_off, report_off = arm(False)
    streams_on, report_on = arm(True)
    assert "spec" not in report_off and report_on["spec"]["drafted"] > 0
    assert set(streams_off) == set(streams_on)
    for rid, (sc, word, toks) in sorted(streams_off.items()):
        if sc == "adaptive_depth":
            continue                   # excluded from exactness by contract
        assert streams_on[rid] == (sc, word, toks), rid
    for sc, block in report_on["spec"]["scenarios"].items():
        assert 0 <= block["accepted"] <= block["drafted"] or sc
        assert "accept_rate" in block


# ---------------------------------------------------------------------------
# One compiled program per phase: zero AOT misses after warm_start.
# ---------------------------------------------------------------------------

def test_zero_recompile_after_warm_start(tiny):
    _, _, tok, _ = tiny
    eng = make_engine(tiny, SpecServeEngine)
    aot.reset()
    eng.warm_start()
    run_sched(eng, tok, [(n, "Give me a hint") for n in LOSSLESS])
    stats = aot.stats()
    for name in (eng.aot_draft, eng.aot_verify):
        st = stats[name]
        assert st["misses"] == 0 and st["fallbacks"] == 0, (name, st)
        assert st["hits"] > 0, (name, st)


# ---------------------------------------------------------------------------
# Adaptive depth (the opt-in dial).
# ---------------------------------------------------------------------------

def test_adaptive_depth_dial(tiny):
    """An adaptive session (margin 0: every positive lens gap clears)
    exits early and reports agreement; the lossless sessions sharing the
    batch still match the vanilla arm exactly."""
    _, _, tok, _ = tiny
    scenarios = default_scenarios(max_new_tokens=6, adaptive_exit_margin=0.0)
    specs = [("chat", "Give me a hint"), ("adaptive_depth", "Give me a hint"),
             ("chat_lens", "a clue about the word")]
    off, _ = run_sched(make_engine(tiny, ServeEngine), tok, specs,
                       scenarios=scenarios, max_new=6)
    off.pop("r001-adaptive_depth")     # excluded from exactness by contract
    eng = make_engine(tiny, SpecServeEngine)
    on, _ = run_sched(eng, tok, specs, scenarios=scenarios, max_new=6)
    adaptive = on.pop("r001-adaptive_depth")
    assert_streams_equal(off, on)
    assert adaptive.ok and len(adaptive.tokens) == 6
    assert adaptive.exited_early > 0
    assert adaptive.early_agreement is not None
    assert 0.0 <= adaptive.early_agreement <= 1.0
    lossless = [r for r in on.values()]
    assert all(r.exited_early == 0 for r in lossless)
    assert eng.accept_stats()["exited_early"] == adaptive.exited_early


# ---------------------------------------------------------------------------
# The serve.spec.verify fault site.
# ---------------------------------------------------------------------------

def test_spec_verify_transient_fault_retries_in_place(tiny, tmp_path):
    """times=1 transient: the block retries once (serve.spec.retry event),
    nothing is quarantined, streams complete."""
    _, _, tok, _ = tiny
    inj = FaultInjector()
    inj.arm("serve.spec.verify", times=1, match="r001")
    resilience.set_injector(inj)
    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path)
    try:
        done, sched = run_sched(make_engine(tiny, SpecServeEngine), tok,
                                [("chat", "Give me a hint"),
                                 ("chat_lens", "a clue")])
    finally:
        obs.deactivate(t)
    assert sched.quarantined == 0 and all(r.ok for r in done.values())
    events = list(obs.iter_events(path))
    retries = [e for e in events if e.get("ev") == "point"
               and e.get("name") == "serve.spec.retry"]
    assert len(retries) == 1
    assert "r001" in str(retries[0].get("attrs", {}).get("request"))


def test_spec_verify_permanent_fault_quarantines_one_session(tiny):
    """A permanent fault matching ONE request quarantines exactly that
    session; every other slot keeps decoding to completion."""
    _, _, tok, _ = tiny
    inj = FaultInjector()
    inj.arm("serve.spec.verify", kind="permanent", match="poison")
    resilience.set_injector(inj)
    specs = [("chat", "Give me a hint"), ("chat_lens", "a clue"),
             ("sae_ablate", "the word is")]
    scenarios = default_scenarios(max_new_tokens=5)
    done = {}
    sched = SlotScheduler(
        make_engine(tiny, SpecServeEngine), lens_target_id=-1,
        on_complete=lambda r: done.__setitem__(r.id, r))
    for i, (name, prompt) in enumerate(specs):
        rid = "poison-r001" if i == 1 else f"r{i:03d}-{name}"
        assert sched.submit(Request(id=rid, prompt=prompt,
                                    scenario=scenarios[name], seed=100 + i))
    sched.run_until_idle()
    bad = done.pop("poison-r001")
    assert not bad.ok and bad.finish == "quarantined"
    assert "InjectedPermanentFault" in bad.error
    assert sched.quarantined == 1 and sched.completed == 2
    assert all(r.ok and len(r.tokens) == 5 for r in done.values())


def test_spec_verify_fault_plan_env(tiny, monkeypatch):
    """The seeded TABOO_FAULT_PLAN path reaches the new site."""
    _, _, tok, _ = tiny
    monkeypatch.setenv("TABOO_FAULT_PLAN", json.dumps({
        "serve.spec.verify": {"mode": "fail", "kind": "permanent",
                              "times": 1, "match": "poison"}}))
    resilience.set_injector(None)      # re-read from env
    scenarios = default_scenarios(max_new_tokens=4)
    done = {}
    sched = SlotScheduler(
        make_engine(tiny, SpecServeEngine, slots=2), lens_target_id=-1,
        on_complete=lambda r: done.__setitem__(r.id, r))
    assert sched.submit(Request(id="poison-env", prompt="Give me a hint",
                                scenario=scenarios["chat"], seed=1))
    assert sched.submit(Request(id="clean", prompt="a clue",
                                scenario=scenarios["chat"], seed=2))
    sched.run_until_idle()
    assert not done["poison-env"].ok
    assert done["poison-env"].finish == "quarantined"
    assert done["clean"].ok


# ---------------------------------------------------------------------------
# Plan resolution at admission (env > calibration artifact > heuristic).
# ---------------------------------------------------------------------------

def test_plan_env_override_and_clamp(tiny, monkeypatch):
    params, cfg, tok, sae = tiny
    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "99")   # clamped to L-2
    monkeypatch.setenv("TBX_SPEC_BLOCK", "4")
    eng = make_engine(tiny, SpecServeEngine)
    assert eng.draft_layer == cfg.num_layers - 2
    assert eng.block == 4
    assert eng.plans[None].source == "env"
    # Admission writes the per-slot draft budget from the plan.
    eng.admit(0, tok.encode(chat.user_prompt("hint")), max_new=4)
    assert int(eng.spec.block[0]) == 4
    assert float(eng.spec.margin[0]) == -1.0           # lossless default


def test_plan_calibration_artifact(tiny, monkeypatch, tmp_path):
    params, cfg, tok, sae = tiny
    monkeypatch.delenv("TBX_SPEC_DRAFT_LAYER", raising=False)
    monkeypatch.delenv("TBX_SPEC_BLOCK", raising=False)
    art = tmp_path / "spec_calibration.json"
    art.write_text(json.dumps({
        "words": {"ship": {"draft_layer": 1, "block_size": 5}},
        "default": {"draft_layer": 1, "block_size": 2}}))
    monkeypatch.setenv("TBX_SPEC_CALIBRATION", str(art))
    plan = speculate.resolve_plan(cfg, "ship")
    assert (plan.draft_layer, plan.block_size) == (1, 5)
    assert plan.source == "calibration"
    # A single-word engine resolves without a word -> the default block.
    eng = make_engine(tiny, SpecServeEngine)
    assert eng.draft_layer == 1 and eng.block == 2
    # Explicit constructor overrides beat everything (bench A/B knob).
    eng2 = make_engine(tiny, SpecServeEngine, draft_layer=0, block_size=1)
    assert eng2.draft_layer == 0 and eng2.block == 1


# ---------------------------------------------------------------------------
# Calibrator: the batch-width cost term.
# ---------------------------------------------------------------------------

def test_block_cost_batch_width_term(tiny):
    """Per-row weight streams deflate as 1/rows while the per-row KV
    re-read is flat — so the marginal-draft/verify cost ratio falls
    monotonically with occupancy."""
    _, cfg, _, _ = tiny
    prev_ratio = None
    prev_verify = None
    for rows in (1, 4, 16, 64):
        draft, verify, vanilla = spec_calibrate.block_cost(
            cfg, 1, 1, rows=rows, seq_len=64)
        assert 0 < draft < verify and verify == vanilla
        if prev_ratio is not None:
            assert draft / verify < prev_ratio
            assert verify < prev_verify
        prev_ratio, prev_verify = draft / verify, verify


def test_calibrated_block_grows_with_occupancy(tiny):
    """The serving engine calibrates at its slot count: at fixed agreement
    the chosen G is nondecreasing in rows (and strictly larger at high
    occupancy than the offline rows=1 plan for mid agreement)."""
    _, cfg, _, _ = tiny
    agreement = [0.6] * cfg.num_layers
    gs = [spec_calibrate.calibrate_word(
        agreement, cfg, max_block=8, rows=r)["block_size"]
        for r in (1, 8, 64)]
    assert gs == sorted(gs), gs
    assert gs[-1] > gs[0], gs
    assert all(1 <= g <= 8 for g in gs)


# ---------------------------------------------------------------------------
# Reporting surfaces: loadgen report, trace_report, bench_compare.
# ---------------------------------------------------------------------------

def test_loadgen_spec_report_and_trace_stream(tiny, tmp_path):
    """One speculative loadgen run feeds three gates: the report's spec
    block, the trace_report serving section's speculation line, and the
    --check invariant that every verify span carries an accept record."""
    del tiny
    path = str(tmp_path / "_events.jsonl")
    engine, scenarios, lens_tgt = loadgen.build_synthetic_engine(
        slots=3, max_new_tokens=5, speculative=True)
    t = obs.activate(path)
    try:
        report = loadgen.run_inprocess(
            engine, n_requests=8, seed=3, rate=500.0, concurrency=6,
            scenarios=scenarios, lens_target_id=lens_tgt)
    finally:
        obs.deactivate(t)
    assert report["config"]["speculative"] is True
    spec = report["spec"]
    assert spec["drafted"] >= spec["accepted"] >= 0
    assert 0.0 <= spec["accept_rate"] <= 1.0
    assert spec["blocks"] > 0 and spec["tokens_per_verify"] > 0
    for block in spec["scenarios"].values():
        assert block["accepted"] <= block["drafted"]

    events = list(obs.iter_events(path))
    assert trace_report.check_serve_spec(path, events) == []
    spans, points = trace_report.build_spans(events)
    section = trace_report._serving_section([], points, spans)
    assert "speculation:" in section and "acc/step" in section
    assert "wasted-draft share" in section


def _span_events(attrs):
    return [
        {"ev": "start", "id": 1, "name": "serve.spec.verify",
         "kind": "program", "t": 0.0, "seq": 0},
        {"ev": "end", "id": 1, "name": "serve.spec.verify", "dur": 0.01,
         "status": "ok", "attrs": attrs, "seq": 1},
    ]


def test_check_serve_spec_flags_bad_spans():
    good = _span_events({"drafted": 4, "accepted": 2, "emitted": 3})
    assert trace_report.check_serve_spec("ev", good) == []
    missing = trace_report.check_serve_spec(
        "ev", _span_events({"emitted": 3}))
    assert missing and "without an accept record" in missing[0]
    inconsistent = trace_report.check_serve_spec(
        "ev", _span_events({"drafted": 2, "accepted": 5}))
    assert inconsistent and "inconsistent" in inconsistent[0]
    # An unended span is the killed-run case: left to the generic check.
    unended = [dict(good[0])]
    assert trace_report.check_serve_spec("ev", unended) == []


def test_bench_compare_serve_spec_metrics(tmp_path):
    def write(repo, n, parsed):
        os.makedirs(repo, exist_ok=True)
        with open(os.path.join(repo, f"BENCH_r{n}.json"), "w") as f:
            json.dump({"n": n, "parsed": parsed}, f)

    regressed = str(tmp_path / "regressed")
    write(regressed, 1, {"serve_spec_ab": {"spec_speedup": 1.4,
                                           "accept_rate": 0.6}})
    write(regressed, 2, {"serve_spec_ab": {"spec_speedup": 0.7,
                                           "accept_rate": 0.2}})
    _, regressions, rc = bench_compare.compare(regressed)
    assert rc == 1
    assert any("serve_spec_ab.spec_speedup" in r for r in regressions)
    assert any("serve_spec_ab.accept_rate" in r for r in regressions)

    # The stage is env-gated: a round without it is skipped, not failed.
    absent = str(tmp_path / "absent")
    write(absent, 1, {"serve_spec_ab": {"spec_speedup": 1.4,
                                        "accept_rate": 0.6}})
    write(absent, 2, {"value": 1.0})
    lines, regressions, rc = bench_compare.compare(absent)
    assert rc == 0 and not regressions
    assert any("serve_spec_ab.spec_speedup" in ln and "skipped" in ln
               for ln in lines)


# ---------------------------------------------------------------------------
# The env switch and the bench A/B stage.
# ---------------------------------------------------------------------------

def test_env_switch_selects_engine_class(monkeypatch):
    monkeypatch.setenv("TBX_SERVE_SPECULATE", "1")
    assert spec_engine.enabled()
    engine, _, _ = loadgen.build_synthetic_engine(slots=2, max_new_tokens=4)
    assert isinstance(engine, SpecServeEngine)
    monkeypatch.setenv("TBX_SERVE_SPECULATE", "0")
    assert not spec_engine.enabled()
    engine, _, _ = loadgen.build_synthetic_engine(slots=2, max_new_tokens=4)
    assert not isinstance(engine, SpecServeEngine)


def test_bench_serve_spec_ab_stage(tiny, monkeypatch):
    """The committed rollout gate end-to-end: all lossless streams exact,
    accept_rate > 0, zero verify-program recompiles."""
    params, cfg, tok, sae = tiny
    monkeypatch.setenv("BENCH_SERVE_SLOTS", "2")
    monkeypatch.setenv("BENCH_SERVE_SPEC_REQUESTS", "8")
    import bench

    stage = bench._serve_spec_ab(params, cfg, sae, TAP, False)
    assert stage["all_exact"] is True
    assert stage["mismatched_requests"] == []
    assert stage["accept_rate"] > 0
    assert stage["aot"]["misses"] == 0 and stage["aot"]["fallbacks"] == 0
    assert stage["spec_speedup"] > 0
