"""Numerics tests for ops/{lens,sae,projection} against numpy oracles
(SURVEY.md §4 test plan item 2)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.ops import lens, projection, sae


@pytest.fixture(scope="module")
def tiny_model():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_lens_forward_matches_full_probs(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(0)
    B, T = 2, 7
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, T)))
    targets = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B,)))

    res = lens.lens_forward(params, cfg, ids, targets, tap_layer=2, top_k=3)
    all_probs, resid = lens.full_probs_forward(params, cfg, ids, tap_layer=2)

    probs = np.asarray(all_probs)                    # [L, B, T, V]
    # target_prob parity
    expected_tgt = np.stack(
        [probs[:, b, :, int(targets[b])] for b in range(B)], axis=1
    )
    np.testing.assert_allclose(np.asarray(res.tap.target_prob), expected_tgt,
                               atol=1e-6, rtol=1e-5)
    # argmax/topk parity
    np.testing.assert_array_equal(
        np.asarray(res.tap.argmax_id), probs.argmax(axis=-1))
    expected_topk = np.argsort(-probs, axis=-1)[..., :3]
    np.testing.assert_array_equal(np.asarray(res.tap.topk_ids), expected_topk)
    # residual tap parity: full forward per-layer taps
    full = gemma2.forward(params, cfg, ids, per_layer_fn=lambda h, i: h)
    np.testing.assert_allclose(np.asarray(res.residual), np.asarray(full.taps[2]),
                               atol=1e-6, rtol=1e-5)
    assert resid is not None
    np.testing.assert_allclose(np.asarray(resid), np.asarray(full.taps[2]),
                               atol=1e-6, rtol=1e-5)


def test_probs_sum_to_one(tiny_model):
    cfg, params = tiny_model
    ids = jnp.asarray(np.arange(5)[None, :] % cfg.vocab_size)
    all_probs, _ = lens.full_probs_forward(params, cfg, ids)
    np.testing.assert_allclose(np.asarray(all_probs).sum(-1), 1.0, atol=1e-5)


def test_aggregate_masked_sum_matches_reference_zeroing():
    """Oracle reimplementation of reference src/01_reproduce_logit_lens.py:35-71."""
    rng = np.random.default_rng(1)
    T, V, k = 6, 23, 4
    probs = rng.random((T, V)).astype(np.float32)
    token_ids = rng.integers(0, V, size=T)
    response_mask = np.array([False, False, True, True, True, True])

    expected = probs.copy()
    for t in range(T):
        expected[t, token_ids[t]] = 0.0
        if t > 0:
            expected[t, token_ids[t - 1]] = 0.0
    expected[~response_mask] = 0.0
    summed = expected.sum(0)
    exp_ids = np.argsort(-summed)[:k]

    ids, vals = lens.aggregate_masked_sum(
        jnp.asarray(probs), jnp.asarray(token_ids), jnp.asarray(response_mask),
        top_k=k)
    np.testing.assert_array_equal(np.asarray(ids), exp_ids)
    np.testing.assert_allclose(np.asarray(vals), summed[exp_ids], rtol=1e-6)


def test_spike_positions():
    tgt = jnp.asarray([0.1, 0.9, 0.2, 0.8, 0.3])
    mask = jnp.asarray([False, True, True, True, True])
    pos, probs = lens.spike_positions(tgt, mask, top_k=2)
    np.testing.assert_array_equal(np.asarray(pos), [1, 3])
    np.testing.assert_allclose(np.asarray(probs), [0.9, 0.8])


def test_spike_positions_short_response_never_points_at_pad():
    """Fewer response tokens than top_k: surplus slots repeat the best valid
    position instead of returning pad/prompt columns."""
    tgt = jnp.asarray([0.5, 0.4, 0.7, 0.2])
    mask = jnp.asarray([False, False, True, False])   # one response token
    pos, probs = lens.spike_positions(tgt, mask, top_k=3)
    np.testing.assert_array_equal(np.asarray(pos), [2, 2, 2])
    np.testing.assert_allclose(np.asarray(probs), [0.7, 0.0, 0.0])


# ---------------------------------------------------------------------------
# SAE
# ---------------------------------------------------------------------------

def test_sae_jumprelu_gating():
    s = sae.init_random(jax.random.PRNGKey(1), d_model=8, d_sae=16)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(5, 8)), jnp.float32)
    acts = sae.encode(s, x)
    pre = np.asarray(x) @ np.asarray(s.w_enc) + np.asarray(s.b_enc)
    expected = np.where(pre > np.asarray(s.threshold), pre, 0.0)
    np.testing.assert_allclose(np.asarray(acts), expected, atol=1e-5)
    # JumpReLU: activations below threshold but above 0 are OFF
    assert (expected == 0).any()


def test_sae_ablation_identity_when_no_latents():
    s = sae.init_random(jax.random.PRNGKey(3), d_model=8, d_sae=16)
    x = jnp.asarray(np.random.default_rng(4).normal(size=(3, 8)), jnp.float32)
    out = sae.ablate_latents(s, x, jnp.asarray([-1, -1], jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_sae_ablation_removes_latent_contribution():
    s = sae.init_random(jax.random.PRNGKey(5), d_model=8, d_sae=16)
    x = jnp.asarray(np.random.default_rng(6).normal(size=(1, 8)), jnp.float32)
    acts = np.asarray(sae.encode(s, x))
    active = [int(i) for i in np.nonzero(acts[0])[0]]
    assert active, "fixture needs at least one active latent"
    lat = active[0]
    out = sae.ablate_latents(s, x, jnp.asarray([lat], jnp.int32))
    expected = np.asarray(x) - acts[0, lat] * np.asarray(s.w_dec)[lat][None, :]
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_mean_response_acts_masks_prompt():
    s = sae.init_random(jax.random.PRNGKey(7), d_model=8, d_sae=16)
    resid = jnp.asarray(np.random.default_rng(8).normal(size=(4, 8)), jnp.float32)
    mask = jnp.asarray([False, False, True, True])
    mean = sae.mean_response_acts(s, resid, mask)
    acts = np.asarray(sae.encode(s, resid))
    np.testing.assert_allclose(np.asarray(mean), acts[2:].mean(0), atol=1e-5)


def test_ablation_edit_fn_targets_layer_and_positions():
    from taboo_brittleness_tpu.pipelines.interventions import sae_ablation_edit

    s = sae.init_random(jax.random.PRNGKey(9), d_model=8, d_sae=16)
    h = jnp.asarray(np.random.default_rng(10).normal(size=(2, 3, 8)), jnp.float32)
    pos_mask = jnp.asarray([[True, False, True], [False, True, False]])
    acts = np.asarray(sae.encode(s, h))
    lat = int(np.abs(acts).sum(axis=(0, 1)).argmax())
    ep = {"sae": s, "latent_ids": jnp.asarray([lat]), "layer": 1,
          "positions": pos_mask}
    out_wrong_layer = sae_ablation_edit(h, jnp.asarray(0), ep)
    np.testing.assert_allclose(np.asarray(out_wrong_layer), np.asarray(h))
    out = np.asarray(sae_ablation_edit(h, jnp.asarray(1), ep))
    unchanged = ~np.asarray(pos_mask)
    np.testing.assert_allclose(out[unchanged], np.asarray(h)[unchanged])


# ---------------------------------------------------------------------------
# Projection
# ---------------------------------------------------------------------------

def test_principal_subspace_recovers_planted_direction():
    rng = np.random.default_rng(11)
    d, n = 16, 200
    u_true = np.zeros(d); u_true[3] = 1.0
    data = rng.normal(size=(n, 1)) * 10.0 @ u_true[None, :] + 0.01 * rng.normal(size=(n, d))
    u, var = projection.principal_subspace(jnp.asarray(data, jnp.float32), rank=1)
    cos = abs(float(np.asarray(u)[:, 0] @ u_true))
    assert cos > 0.999
    assert float(var[0]) > 50.0


def test_remove_subspace_is_projection():
    rng = np.random.default_rng(12)
    d, r = 16, 4
    u = projection.random_subspace(jax.random.PRNGKey(0), d, r)
    un = np.asarray(u)
    np.testing.assert_allclose(un.T @ un, np.eye(r), atol=1e-5)  # orthonormal
    x = jnp.asarray(rng.normal(size=(5, d)), jnp.float32)
    out = np.asarray(projection.remove_subspace(x, u))
    # residual is orthogonal to the subspace, and idempotent
    np.testing.assert_allclose(out @ un, 0.0, atol=1e-4)
    out2 = np.asarray(projection.remove_subspace(jnp.asarray(out), u))
    np.testing.assert_allclose(out2, out, atol=1e-5)
