"""End-to-end request tracing (ISSUE 19): context propagation, lifecycle
spans, the waterfall assembler, TTFT SLOs, and burn→trace exemplars.

The centerpiece is a chaos e2e: a replica killed mid-decode (``serve.step``
die) leaves its request's first attempt as a dangling span the fleet merge
closes with a synthesized error end; the re-spooled request completes on a
surviving replica as a SECOND attempt under the SAME trace_id, with TTFT
re-timed on the surviving attempt, and ``check_request_traces`` holds on
the merged stream.

Around it: context mint/parse/ensure/for_attempt units, the exemplar
registry (worst-K per series, drain vs peek), SLO cells carrying exemplar
trace ids into the ``tbx top`` burn table and flightrec dumps, the
``check_request_traces`` invariants over hand-built streams, an in-process
serve burst proving spans/TTFT/exemplars land end to end, the legacy
pre-trace-payload path (synthetic mint at claim + one-shot warn), and the
``serve_latency.ttft_p99`` bench_compare gate.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from taboo_brittleness_tpu.obs import reqtrace, slo, top
from taboo_brittleness_tpu.obs import trace as trace_mod
from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump
from taboo_brittleness_tpu.serve.server import RequestSpool

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_exemplars():
    reqtrace.reset_exemplars()
    yield
    reqtrace.reset_exemplars()


# ---------------------------------------------------------------------------
# Context mint / parse / propagation units.
# ---------------------------------------------------------------------------


def test_mint_parse_roundtrip():
    ctx = reqtrace.mint()
    assert ctx["v"] == reqtrace.CTX_VERSION
    assert len(ctx["trace_id"]) == 16 and ctx["attempt"] == 0
    parsed = reqtrace.parse({reqtrace.CTX_KEY: ctx})
    assert parsed is not None and parsed["trace_id"] == ctx["trace_id"]
    assert "synthetic" not in parsed


def test_parse_rejects_newer_version_and_garbage():
    newer = {**reqtrace.mint(), "v": reqtrace.CTX_VERSION + 1}
    assert reqtrace.parse({reqtrace.CTX_KEY: newer}) is None
    assert reqtrace.parse({reqtrace.CTX_KEY: "not-a-dict"}) is None
    assert reqtrace.parse({reqtrace.CTX_KEY: {"v": 1}}) is None  # no id
    assert reqtrace.parse({"id": "r0"}) is None
    assert reqtrace.parse(None) is None


def test_ensure_is_idempotent_and_marks_synthetic_mints():
    payload, ctx, minted = reqtrace.ensure({"id": "r0"}, synthetic=True)
    assert minted and ctx["synthetic"] is True
    assert payload[reqtrace.CTX_KEY]["trace_id"] == ctx["trace_id"]
    again, ctx2, minted2 = reqtrace.ensure(payload)
    assert not minted2 and ctx2["trace_id"] == ctx["trace_id"]
    assert again is payload


def test_for_attempt_keeps_trace_and_records_dead_holders():
    ctx = reqtrace.mint()
    child = reqtrace.for_attempt(ctx, 1, dead_holder="w1-i0")
    assert child["trace_id"] == ctx["trace_id"]
    assert child["attempt"] == 1 and child["dead"] == ["w1-i0"]
    grand = reqtrace.for_attempt(child, 2, dead_holder="w0-i1")
    assert grand["trace_id"] == ctx["trace_id"]
    assert grand["dead"] == ["w0-i1", "w1-i0"]


# ---------------------------------------------------------------------------
# Exemplar registry.
# ---------------------------------------------------------------------------


def test_exemplars_keep_worst_k_and_drain(monkeypatch):
    monkeypatch.setenv("TBX_TRACE_EXEMPLARS", "2")
    for tid, v in (("aa", 0.1), ("bb", 0.9), ("cc", 0.5)):
        reqtrace.note_exemplar("serve.latency.chat", tid, v)
    assert reqtrace.take_exemplars("serve.latency.chat") == ["bb", "cc"]
    # Drained: the current window is empty, but peek still serves the last
    # drained window (flightrec dumps fire between windows).
    assert reqtrace.take_exemplars("serve.latency.chat") == []
    assert reqtrace.peek_exemplars() == {"serve.latency.chat": ["bb", "cc"]}


def test_exemplars_disabled_at_zero_cap(monkeypatch):
    monkeypatch.setenv("TBX_TRACE_EXEMPLARS", "0")
    reqtrace.note_exemplar("serve.latency.chat", "aa", 1.0)
    assert reqtrace.peek_exemplars() == {}


def test_slo_engine_attaches_exemplars_to_histogram_cells():
    reqtrace.note_exemplar("serve.ttft.chat", "deadbeefcafef00d", 9.0)
    engine = slo.SloEngine(emit_alerts=False)
    block = engine.observe_window(
        dur=1.0, hists={"serve.ttft.chat": {"samples": [9.0]}},
        counter_deltas={}, gauges={})
    cell = block["serve_ttft.chat"]
    assert cell["exemplars"] == ["deadbeefcafef00d"]
    assert not cell["ok"], "9s TTFT must burn the default 1s objective"


def test_top_burn_table_renders_exemplar_trace_ids():
    lines = top._slo_lines({"slo": {"serve_ttft.chat": {
        "burn": 5.0, "fast": 5.0, "slow": 5.0, "ok": False,
        "exemplars": ["deadbeefcafef00d"]}}})
    assert any("deadbeefcafef00d" in ln for ln in lines)


def test_flightrec_dump_carries_exemplars(tmp_path):
    from taboo_brittleness_tpu.obs import flightrec

    reqtrace.note_exemplar("serve.latency.chat", "feedfacefeedface", 2.0)
    rec = flightrec.FlightRecorder(capacity=8)
    rec.configure(str(tmp_path))
    rec.record("test.tick")
    path = rec.dump("test")
    assert path is not None
    with open(path) as f:
        payload = json.load(f)
    assert payload["exemplars"]["serve.latency.chat"] == ["feedfacefeedface"]


# ---------------------------------------------------------------------------
# check_request_traces over hand-built streams.
# ---------------------------------------------------------------------------


def _span(i, req, *, trace="t0", attempt=0, worker=None, parent=None,
          t=0.0):
    ev = {"v": 1, "seq": i, "t": t, "ev": "start", "kind": "request",
          "name": "serve.request", "id": i,
          "attrs": {"request": req, "trace": trace, "attempt": attempt}}
    if worker is not None:
        ev["worker"] = worker
    if parent is not None:
        ev["parent"] = parent
    return ev


def _end(i, seq, *, status="ok", terminal=True, emitted=2, ttft=0.01,
         synthesized=False, worker=None, t=1.0):
    attrs = {}
    if terminal:
        attrs.update({"terminal": True, "emitted": emitted})
        if ttft is not None:
            attrs["ttft_seconds"] = ttft
    if synthesized:
        attrs["synthesized"] = True
    ev = {"v": 1, "seq": seq, "t": t, "ev": "end", "kind": "request",
          "name": "serve.request", "id": i, "dur": t, "status": status,
          "attrs": attrs}
    if worker is not None:
        ev["worker"] = worker
    return ev


def test_check_request_traces_clean_single_attempt():
    events = [_span(1, "r0"), _end(1, 2)]
    assert trace_report.check_request_traces("x", events) == []


def test_check_request_traces_noop_on_plain_streams():
    events = [{"v": 1, "seq": 1, "t": 0.0, "ev": "start", "kind": "run",
               "name": "sweep", "id": 1}]
    assert trace_report.check_request_traces("x", events) == []


def test_check_request_traces_flags_unresolved_request():
    events = [_span(1, "r0"),
              _end(1, 2, status="error", terminal=False)]
    errs = trace_report.check_request_traces("x", events)
    assert any("never resolved" in e for e in errs)


def test_check_request_traces_flags_trace_disagreement():
    events = [_span(1, "r0", trace="t0"), _end(1, 3, terminal=False,
                                               status="error"),
              _span(2, "r0", trace="OTHER", attempt=1), _end(2, 4)]
    errs = trace_report.check_request_traces("x", events)
    assert any("disagree on trace id" in e for e in errs)


def test_check_request_traces_respool_chain_is_clean():
    # Attempt 0 killed mid-decode (synthesized close), attempt 1 terminal.
    events = [_span(1, "r0", worker="w1"),
              _end(1, 2, status="error", terminal=False, synthesized=True,
                   worker="w1"),
              _span(3, "r0", attempt=1, worker="w0"),
              _end(3, 4, worker="w0")]
    assert trace_report.check_request_traces("x", events) == []


def test_check_request_traces_flags_unexplained_double_terminal():
    events = [_span(1, "r0", worker="w0"), _end(1, 2, worker="w0"),
              _span(3, "r0", attempt=1, worker="w2"), _end(3, 4,
                                                           worker="w2")]
    errs = trace_report.check_request_traces("x", events)
    assert any("resolves exactly once" in e for e in errs)


def test_check_request_traces_allows_killed_incarnation_orphan():
    # w1 finished decode (terminal flushed) then died before its commit:
    # the extra terminal is explained by w1's synthesized ends elsewhere.
    events = [_span(1, "r0", worker="w1"), _end(1, 2, worker="w1"),
              # another span of the killed incarnation, merge-closed
              _span(3, "r1", worker="w1"),
              _end(3, 4, status="error", terminal=False, synthesized=True,
                   worker="w1"),
              _span(5, "r1", attempt=1, worker="w0"), _end(5, 6,
                                                           worker="w0"),
              _span(7, "r0", attempt=1, worker="w0"), _end(7, 8,
                                                           worker="w0")]
    assert trace_report.check_request_traces("x", events) == []


def test_check_request_traces_allows_duplicate_dispatch():
    events = [_span(1, "r0", worker="w0"), _end(1, 2, worker="w0"),
              _span(3, "r0", attempt=1, worker="w2"),
              _end(3, 4, worker="w2"),
              {"v": 1, "seq": 5, "t": 2.0, "ev": "point", "kind": "point",
               "name": "serve.respond",
               "attrs": {"request": "r0", "duplicate": True}}]
    assert trace_report.check_request_traces("x", events) == []


def test_check_request_traces_flags_missing_ttft():
    events = [_span(1, "r0"), _end(1, 2, ttft=None)]
    errs = trace_report.check_request_traces("x", events)
    assert any("no ttft_seconds" in e for e in errs)


def test_check_request_traces_flags_floating_first_token():
    events = [_span(1, "r0"), _end(1, 2),
              {"v": 1, "seq": 3, "t": 0.5, "ev": "point", "kind": "point",
               "name": "serve.first_token", "parent": 999,
               "attrs": {"request": "r0", "ttft_seconds": 0.01}}]
    errs = trace_report.check_request_traces("x", events)
    assert any("floating TTFT" in e for e in errs)


def test_check_request_traces_flags_synthesized_terminal():
    events = [_span(1, "r0", worker="w1"),
              _end(1, 2, status="error", synthesized=True, worker="w1")]
    errs = trace_report.check_request_traces("x", events)
    assert any("merge-synthesized" in e for e in errs)


# ---------------------------------------------------------------------------
# In-process serve burst: spans, TTFT, exemplars land end to end.
# ---------------------------------------------------------------------------


def test_inprocess_serve_traces_end_to_end(tmp_path):
    from taboo_brittleness_tpu import obs
    from taboo_brittleness_tpu.serve import loadgen

    engine, scen, tgt = loadgen.build_synthetic_engine(max_new_tokens=4)
    out = str(tmp_path / "serve")
    responses = []
    with obs.sweep_observer(out, pipeline="serve-test"):
        report = loadgen.run_inprocess(
            engine, n_requests=6, seed=3, rate=500.0, concurrency=6,
            scenarios=scen, lens_target_id=tgt,
            on_complete=responses.append)

    ok = [r for r in responses if r.ok]
    assert ok, "burst produced no completions"
    assert all(r.trace_id for r in responses), "responses must be stamped"
    for r in ok:
        assert r.ttft_seconds is not None
        assert 0 < r.ttft_seconds <= r.latency_seconds + 1e-9

    # The report grew TTFT histogram blocks next to latency.
    assert report["overall_ttft"]["count"] == len(ok)
    for block in report["scenarios"].values():
        assert block["ttft"]["count"] > 0

    events_path = os.path.join(out, "_events.jsonl")
    events = list(trace_mod.iter_events(events_path))
    assert trace_report.check_request_traces(events_path, events) == []

    # Every completion's trace_id resolves through the assembler, with the
    # TTFT riding the terminal attempt.
    traces = reqtrace.assemble([events_path])
    for r in ok:
        tr = traces[r.trace_id]
        term = tr.terminal_attempt
        assert term is not None and term.status == "ok"
        assert term.attrs.get("ttft_seconds") == pytest.approx(
            r.ttft_seconds)
        assert reqtrace.render(tr)

    # Completions registered burn→trace exemplars for both series families.
    ex = reqtrace.peek_exemplars()
    assert any(k.startswith("serve.latency.") for k in ex)
    assert any(k.startswith("serve.ttft.") for k in ex)


def test_scheduler_latency_percentiles_carry_ttft():
    from taboo_brittleness_tpu.obs import metrics as obs_metrics
    from taboo_brittleness_tpu.serve import loadgen
    from taboo_brittleness_tpu.serve.scheduler import SlotScheduler

    obs_metrics.reset()  # percentiles read the process-global histograms
    engine, scen, tgt = loadgen.build_synthetic_engine(max_new_tokens=4)
    sched = SlotScheduler(engine, lens_target_id=tgt)
    engine.warm_start()
    plan = loadgen.build_schedule(
        4, seed=0, rate=0.0, mix={"chat": 1.0},
        scenarios=scen, prompts=("Give me a hint",))
    for _, req in plan:
        assert sched.submit(req)
    while sched.in_flight or sched.queue_depth:
        sched.step()
    pct = sched.latency_percentiles()
    chat = pct["scenarios"]["chat"]
    assert chat["ttft"]["cumulative"]["n"] == 4
    assert 0 < chat["ttft"]["cumulative"]["p99_s"] <= (
        chat["cumulative"]["p99_s"] + 1e-9)


# ---------------------------------------------------------------------------
# Legacy pre-trace payloads (satellite: mid-upgrade spools keep serving).
# ---------------------------------------------------------------------------


def test_legacy_pretrace_requests_still_serve(tmp_path):
    out = str(tmp_path / "spool")
    n = 3
    spool = RequestSpool(out)
    # Old-format request files: no trace context, written straight into the
    # intake (bypassing RequestSpool.put, which would mint one).
    for i in range(n):
        atomic_json_dump(
            {"id": f"old{i:02d}", "prompt": "Give me a hint",
             "scenario": "chat", "seed": i},
            os.path.join(spool.requests_dir, f"old{i:02d}.json"))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("TABOO_FAULT_PLAN", None)
    env.pop("TBX_WORKER_ID", None)
    proc = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu", "serve",
         "--synthetic", "--output-dir", out, "--slots", "4",
         "--poll", "0.02", "--max-new-tokens", "4",
         "--max-requests", str(n)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]

    for i in range(n):
        resp = spool.get_response(f"old{i:02d}")
        assert resp is not None and resp["ok"], resp
        # Context minted at claim: the response is traceable from that hop.
        assert resp["trace_id"] and resp["attempt"] == 0
        assert resp["ttft_seconds"] is not None

    # The mint warned ONCE, not per request.
    warns = [ev for ev in trace_mod.iter_events(
        os.path.join(out, "_events.jsonl"))
        if ev.get("name") == "serve.pretrace_request"]
    assert len(warns) == 1, f"expected one-shot warn, got {len(warns)}"


# ---------------------------------------------------------------------------
# The chaos acceptance e2e: one trace across replica death.
# ---------------------------------------------------------------------------


def test_chaos_respool_keeps_one_trace_across_death(tmp_path, monkeypatch):
    """Replica w1 dies mid-decode (``serve.step`` die): its in-flight
    request's first attempt is closed by the fleet merge with a synthesized
    error end, the re-spooled request completes elsewhere as attempt 1
    under the SAME trace_id with TTFT re-timed on the surviving attempt,
    and ``check_request_traces`` holds on the merged stream."""
    from taboo_brittleness_tpu.runtime import resilience, supervise
    from taboo_brittleness_tpu.serve.replica import chaos_smoke

    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())
    for key in ("TABOO_FAULT_PLAN", "TBX_INCARNATION", "TBX_WORKER_ID"):
        monkeypatch.delenv(key, raising=False)
    monkeypatch.setenv("TBX_OBS_PROGRESS_S", "0.2")
    monkeypatch.setenv("TBX_SUPERVISE_BACKOFF_S", "0")

    out = str(tmp_path / "fleet")
    plan = {"serve.step": [
        {"mode": "die", "times": 1, "match": "w1", "incarnation": 0}]}
    res = chaos_smoke(out, n_requests=9, fault_plan=plan)
    assert res.status == "done" and res.exit_code == 0, res.to_dict()
    assert res.respooled >= 1, "the die fault never forced a re-spool"

    events_path = os.path.join(out, "_events.jsonl")
    events = list(trace_mod.iter_events(events_path))
    assert trace_report.check_request_traces(events_path, events) == []

    traces = reqtrace.assemble([events_path])
    chains = [t for t in traces.values() if len(t.attempts) > 1]
    assert chains, "no multi-attempt trace despite a re-spool"
    for tr in chains:
        # ONE trace: every attempt span of the request carries this id.
        for ev in events:
            attrs = ev.get("attrs") or {}
            if (ev.get("ev") == "start" and ev.get("kind") == "request"
                    and attrs.get("request") == tr.request):
                assert attrs.get("trace") == tr.trace_id
        # Exactly one attempt carries the ok terminal, and the response
        # file resolves the same trace at that attempt.
        terminals = [a for a in tr.attempts if a.terminal]
        winners = [a for a in terminals if a.status == "ok"]
        assert len(winners) == 1, tr.request
        spool = RequestSpool(out, fleet=True)
        resp = spool.get_response(tr.request)
        assert resp is not None and resp["trace_id"] == tr.trace_id
        assert resp["attempt"] == winners[0].number

    # At least one chain crossed the DEATH: under full-suite load a lease
    # can also expire on a merely-slow holder (duplicate-respond path), so
    # only chains whose early attempt was merge-synthesized must show the
    # acceptance shape — and the serve.step die guarantees one exists.
    death_chains = [t for t in chains
                    if any(a.synthesized for a in t.attempts)]
    assert death_chains, "no chain crossed the replica death"
    for tr in death_chains:
        attempts = sorted(tr.attempts, key=lambda a: a.number)
        dead = next(a for a in attempts if a.synthesized)
        survivor = attempts[-1]
        # Died attempt: closed by the merge, never terminal.
        assert dead.status == "error" and not dead.terminal
        # Surviving attempt: terminal, and TTFT timed on THIS attempt.
        assert survivor.terminal and survivor.status == "ok"
        assert survivor.number > dead.number
        if float(survivor.attrs.get("emitted", 0) or 0) > 0:
            assert survivor.attrs.get("ttft_seconds") is not None
        # And the waterfall renders the death + recovery.
        text = reqtrace.render(tr)
        assert "DIED" in text and "attempt" in text

    # The full drift gate stays green on the merged stream.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         "--check", events_path],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# Fixture + CLI gates.
# ---------------------------------------------------------------------------


def test_committed_serve_fleet_fixture_passes_trace_selfcheck():
    fixture = os.path.join(REPO, "tests", "fixtures", "obs", "serve_fleet")
    assert os.path.isdir(fixture), "serve_fleet fixture missing"
    assert reqtrace.selfcheck(fixture) == 0


def test_trace_cli_resolves_fixture_request(capsys):
    fixture = os.path.join(REPO, "tests", "fixtures", "obs", "serve_fleet")
    traces = reqtrace.assemble(reqtrace.find_event_files(fixture))
    tid = next(t.trace_id for t in traces.values()
               if t.terminal_attempt is not None
               and not t.trace_id.startswith("("))
    assert reqtrace.main([fixture, "--trace", tid]) == 0
    assert tid in capsys.readouterr().out


# ---------------------------------------------------------------------------
# bench_compare: the serve_latency.ttft_p99 regression gate.
# ---------------------------------------------------------------------------


def _write_round(tmp_path, n, extra):
    payload = {"n": n, "parsed": {"value": 20.0, **extra}}
    with open(str(tmp_path / f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(payload, f)


def test_bench_compare_ttft_within_band(tmp_path):
    _write_round(tmp_path, 1, {"serve_latency": {"ttft_p99": 0.10}})
    _write_round(tmp_path, 2, {"serve_latency": {"ttft_p99": 0.13}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions


def test_bench_compare_ttft_flags_regression(tmp_path):
    _write_round(tmp_path, 1, {"serve_latency": {"ttft_p99": 0.10}})
    _write_round(tmp_path, 2, {"serve_latency": {"ttft_p99": 0.30}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("serve_latency.ttft_p99" in r for r in regressions)


def test_bench_compare_round_without_ttft_skips_with_note(tmp_path):
    _write_round(tmp_path, 1, {"serve_latency": {"p99_s": 0.5}})
    _write_round(tmp_path, 2, {"serve_latency": {"p99_s": 0.5,
                                                 "ttft_p99": 0.1}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions
