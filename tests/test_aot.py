"""AOT warm-start subsystem (runtime/aot.py + jax_cache.AotStore +
interventions.study_program_specs): the registry serves warm-started
executables to the real study call sites with zero misses, results are
identical to the plain jit path, and executables round-trip the on-disk
store across (simulated) processes."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.config import (
    Config, ExperimentConfig, InterventionConfig, ModelConfig)
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.ops import sae as sae_ops
from taboo_brittleness_tpu.pipelines import interventions as iv
from taboo_brittleness_tpu.runtime import aot, jax_cache
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(11), cfg)
    tok = WordTokenizer([WORD, "hint", "clue", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=5),
        intervention=InterventionConfig(
            budgets=(1, 2), random_trials=2, ranks=(1, 2), spike_top_k=2),
        word_plurals={WORD: [WORD, WORD + "s"]},
        prompts=["Give me a hint", "a clue"],
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), d_model=cfg.hidden_size,
                              d_sae=32)
    return params, cfg, tok, config, sae


@pytest.fixture()
def fresh_registry():
    aot.reset()
    yield
    aot.reset()


# ---------------------------------------------------------------------------
# Registry mechanics.
# ---------------------------------------------------------------------------

def test_signature_separates_shapes_dtypes_weakness_and_statics(fresh_registry):
    e = aot.entry("sig", jax.jit(lambda x: x))
    base = e.signature({"x": jnp.zeros((2, 3), jnp.float32)}, {"k": 1})
    assert base == e.signature({"x": jnp.ones((2, 3), jnp.float32)}, {"k": 1})
    assert base != e.signature({"x": jnp.zeros((3, 2), jnp.float32)}, {"k": 1})
    assert base != e.signature({"x": jnp.zeros((2, 3), jnp.int32)}, {"k": 1})
    assert base != e.signature({"x": jnp.zeros((2, 3), jnp.float32)}, {"k": 2})
    # Weak-typed python scalars compile differently from strong arrays: the
    # key must see the difference (a mismatch would make Compiled.call fail).
    assert (e.signature({"x": 1.0}, {})
            != e.signature({"x": jnp.zeros((), jnp.float32)}, {}))
    assert e.signature({"x": 1.0}, {}) == e.signature({"x": 2.0}, {})


def test_build_then_call_hits_and_matches_jit(fresh_registry):
    fn = jax.jit(lambda x, *, scale: x * scale)
    e = aot.entry("mul", fn)
    dyn = {"x": jnp.arange(4.0), "scale": jnp.asarray(3.0)}
    rec = e.build(dyn, {}, execute=True)
    assert rec["source"] == "compiled"
    assert rec["trace_seconds"] >= 0 and rec["compile_seconds"] >= 0
    out = e.call({"x": jnp.arange(4.0), "scale": jnp.asarray(3.0)}, {})
    np.testing.assert_array_equal(np.asarray(out), np.arange(4.0) * 3)
    assert e.hits == 1 and e.misses == 0
    # A different signature misses and takes the jit path.
    out2 = e.call({"x": jnp.arange(8.0), "scale": jnp.asarray(3.0)}, {})
    assert np.asarray(out2).shape == (8,)
    assert e.misses == 1


def test_dispatch_disabled_env_is_plain_jit(fresh_registry, monkeypatch):
    monkeypatch.setenv("TBX_AOT", "0")
    fn = jax.jit(lambda x: x + 1)
    out = aot.dispatch("off", fn, dynamic={"x": jnp.zeros((2,))}, static={})
    np.testing.assert_array_equal(np.asarray(out), np.ones((2,)))
    assert "off" not in aot.stats()          # registry never touched


# ---------------------------------------------------------------------------
# Warm start covers the study exactly (the drift gate).
# ---------------------------------------------------------------------------

def test_warm_start_then_study_zero_misses(setup, fresh_registry):
    """THE guard that keeps study_program_specs honest: after a warm start,
    the real study must run entirely on warm-started programs.  If a
    pipeline change alters any launch signature, this fails loudly instead
    of silently re-introducing the 73-second first word."""
    params, cfg, tok, config, sae = setup
    rep = iv.warm_start_study(params, cfg, tok, config, sae, store=None)
    assert rep["errors"] == 0
    assert {r["label"].split("[")[0] for r in rep["programs"]} >= {
        "decode", "readout", "nll"}
    res = iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    assert set(res["ablation"]["budgets"]) == {"1", "2"}
    s = aot.stats()
    for name in ("decode", "readout", "nll"):
        assert s[name]["misses"] == 0, (name, s)
        assert s[name]["fallbacks"] == 0, (name, s)
        assert s[name]["hits"] > 0, (name, s)


def test_aot_study_results_identical_to_plain_jit(setup, fresh_registry,
                                                  monkeypatch):
    params, cfg, tok, config, sae = setup
    monkeypatch.setenv("TBX_AOT", "0")
    plain = iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    monkeypatch.setenv("TBX_AOT", "1")
    iv.warm_start_study(params, cfg, tok, config, sae, store=None)
    warm = iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    assert (json.dumps(plain, sort_keys=True, default=float)
            == json.dumps(warm, sort_keys=True, default=float))


def test_studies_driver_sync_warm_start(setup, fresh_registry, tmp_path,
                                        monkeypatch):
    """run_intervention_studies(warm_start='sync') wires the warm start into
    the driver itself (the CLI path) and still writes per-word results."""
    params, cfg, tok, config, sae = setup
    monkeypatch.setenv("TBX_AOT_CACHE", "0")    # no ~/.cache writes from tests

    def loader(word):
        return params, cfg, tok

    out = iv.run_intervention_studies(
        config, model_loader=loader, sae=sae, words=[WORD],
        output_dir=str(tmp_path), warm_start="sync")
    assert WORD in out
    assert os.path.exists(tmp_path / f"{WORD}.json")
    s = aot.stats()
    assert all(s[n]["misses"] == 0 for n in ("decode", "readout", "nll")), s


# ---------------------------------------------------------------------------
# On-disk executable store (cross-process reuse).
# ---------------------------------------------------------------------------

def test_store_round_trip_serves_disk_hits(setup, fresh_registry, tmp_path):
    """Process 1 compiles + stores; 'process 2' (fresh registry) loads every
    program from disk — tracing and compiling both skipped — and the loaded
    executables drive a bit-identical study."""
    params, cfg, tok, config, sae = setup
    store = jax_cache.AotStore(path=str(tmp_path))
    rep1 = iv.warm_start_study(params, cfg, tok, config, sae, store=store)
    if rep1["errors"] or not os.listdir(store.dir):
        pytest.skip("executable serialization unsupported on this backend")
    compiled = [r for r in rep1["programs"] if r.get("source") == "compiled"]
    assert compiled and all(r.get("stored") for r in compiled)

    aot.reset()
    store2 = jax_cache.AotStore(path=str(tmp_path))
    rep2 = iv.warm_start_study(params, cfg, tok, config, sae, store=store2)
    srcs = {r["label"]: r["source"] for r in rep2["programs"]}
    assert all(v in ("disk", "memory", "jit") for v in srcs.values()), srcs
    assert sum(1 for v in srcs.values() if v == "disk") >= 3

    res = iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    assert set(res["projection"]["ranks"]) == {"1", "2"}
    s = aot.stats()
    assert all(s[n]["misses"] == 0 for n in ("decode", "readout", "nll")), s


def test_store_corrupt_entry_is_a_miss(setup, fresh_registry, tmp_path):
    params, cfg, tok, config, sae = setup
    store = jax_cache.AotStore(path=str(tmp_path))
    rep = iv.warm_start_study(params, cfg, tok, config, sae, store=store)
    files = sorted(os.listdir(store.dir)) if store.dir else []
    if rep["errors"] or not files:
        pytest.skip("executable serialization unsupported on this backend")
    victim = os.path.join(store.dir, files[0])
    with open(victim, "wb") as f:
        f.write(b"not a pickle")
    store2 = jax_cache.AotStore(path=str(tmp_path))
    name, key = files[0][:-4].rsplit("-", 1)
    assert store2.load(name, key) is None
    assert os.path.exists(victim + ".corrupt")   # quarantined, not retried


def test_store_dir_keys_on_source_fingerprint(tmp_path):
    store = jax_cache.AotStore(path=str(tmp_path))
    assert jax_cache.source_fingerprint()[:12] in os.path.basename(store.dir)


def test_store_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TBX_AOT_CACHE", "0")
    store = jax_cache.AotStore(path=str(tmp_path))
    assert not store.enabled
    assert store.load("x", "y") is None
    assert store.save("x", "y", object()) is False
