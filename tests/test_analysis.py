"""tbx-check: fixture corpus (exact codes + lines), pragmas, baseline,
deep jaxpr mode, and the repo-wide zero-findings meta-gate."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from taboo_brittleness_tpu.analysis import baseline as baseline_mod
from taboo_brittleness_tpu.analysis.cli import run_check
from taboo_brittleness_tpu.analysis.core import ModuleContext, analyze_file
from taboo_brittleness_tpu.analysis.rules import RULES, RepoContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


def _findings(name):
    active, suppressed = analyze_file(os.path.join(FIXTURES, name))
    return active, suppressed


def _codes_and_lines(findings):
    return sorted((f.code, f.line) for f in findings)


# ---------------------------------------------------------------------------
# One seeded violation (set) per rule, exact codes and line numbers.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,expected", [
    ("tbx001_host_sync.py",
     [("TBX001", 13), ("TBX001", 18), ("TBX001", 19)]),
    ("tbx002_vocab_f32.py",
     [("TBX002", 8), ("TBX002", 9)]),
    ("tbx003_missing_donation.py",
     [("TBX003", 8)]),
    ("tbx004_static_argnames.py",
     [("TBX004", 8), ("TBX004", 19)]),
    ("tbx005_mesh_axis.py",
     [("TBX005", 6), ("TBX005", 11)]),
    ("tbx006_nondeterminism.py",
     [("TBX006", 13), ("TBX006", 14), ("TBX006", 15)]),
    ("tbx007_wallclock.py",
     [("TBX007", 8), ("TBX007", 10), ("TBX007", 15)]),
    ("tbx008_captured_const.py",
     [("TBX008", 10), ("TBX008", 12)]),
])
def test_fixture_rules(name, expected):
    active, _ = _findings(name)
    assert _codes_and_lines(active) == expected


def test_clean_fixture_has_no_findings():
    active, suppressed = _findings("clean.py")
    assert active == [] and suppressed == []


def test_tbx009_fixture_and_path_scope():
    """TBX009 is path-scoped: the same source flags under a package rel,
    stays silent under the analysis/ subpackage (the tbx-check CLI's own
    stdout) and outside the package (tools/, tests/), and honors pragmas."""
    path = os.path.join(FIXTURES, "tbx009_print.py")

    in_pkg, suppressed = analyze_file(
        path, rel="taboo_brittleness_tpu/pipelines/mod.py")
    assert _codes_and_lines(in_pkg) == [("TBX009", 10), ("TBX009", 11)]
    assert [f.code for f in suppressed] == ["TBX009"]       # the pragma'd one

    for exempt_rel in ("taboo_brittleness_tpu/analysis/cli.py",
                       "tools/script.py", "tests/test_x.py"):
        active, _ = analyze_file(path, rel=exempt_rel)
        assert [f for f in active if f.code == "TBX009"] == [], exempt_rel


def test_tbx010_fixture_and_path_scope():
    """TBX010: a registered jit entry point (analysis/deep.py ENTRY_POINTS)
    called directly with no TraceAnnotation/named_scope wrapper flags in
    package code; annotated, pragma'd, traced, and out-of-package calls do
    not."""
    path = os.path.join(FIXTURES, "tbx010_unannotated_dispatch.py")

    in_pkg, suppressed = analyze_file(
        path, rel="taboo_brittleness_tpu/pipelines/mod.py")
    assert _codes_and_lines(in_pkg) == [("TBX010", 16)]
    assert [f.code for f in suppressed] == ["TBX010"]       # the pragma'd one

    for exempt_rel in ("taboo_brittleness_tpu/analysis/deep.py",
                       "tools/trace_report.py", "tests/test_x.py"):
        active, _ = analyze_file(path, rel=exempt_rel)
        assert [f for f in active if f.code == "TBX010"] == [], exempt_rel


def test_tbx010_names_derive_from_deep_registry():
    """The rule's call-site vocabulary IS the deep registry: a new entry
    point is covered the day it is registered, with no second list to
    forget."""
    from taboo_brittleness_tpu.analysis.deep import (
        ENTRY_POINTS, entry_point_names)

    names = entry_point_names()
    assert names == frozenset(n.rsplit(".", 1)[1] for n, _ in ENTRY_POINTS)
    assert "greedy_decode" in names and "serve_step" in names


# ---------------------------------------------------------------------------
# Pragmas.
# ---------------------------------------------------------------------------

def _check_source(tmp_path, source):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(source))
    return analyze_file(str(p))


def test_trailing_pragma_suppresses(tmp_path):
    active, suppressed = _check_source(tmp_path, """\
        import time

        def timed():
            t0 = time.time()  # tbx: wallclock-ok — epoch mark is intended
            return t0
    """)
    assert active == []
    assert [f.code for f in suppressed] == ["TBX007"]


def test_comment_block_pragma_covers_next_statement(tmp_path):
    active, suppressed = _check_source(tmp_path, """\
        import time

        def timed():
            # This epoch mark feeds a log record, not duration math.
            # tbx: TBX007-ok — epoch timestamp intended
            # (see the log schema for why.)
            t0 = time.time()
            return t0
    """)
    assert active == []
    assert [f.code for f in suppressed] == ["TBX007"]


def test_pragma_for_other_rule_does_not_suppress(tmp_path):
    active, _ = _check_source(tmp_path, """\
        import time

        def timed():
            t0 = time.time()  # tbx: f32-ok — wrong rule
            return t0
    """)
    assert [f.code for f in active] == ["TBX007"]


# ---------------------------------------------------------------------------
# Baseline engine.
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_filters_known_findings(tmp_path):
    fixture = os.path.join(FIXTURES, "tbx007_wallclock.py")
    report = run_check([fixture], default_excludes=False)
    assert report.findings

    bl = tmp_path / "baseline.json"
    n = baseline_mod.save(report.findings, str(bl))
    assert n == len({baseline_mod.fingerprint(f) for f in report.findings})
    with open(bl) as f:
        doc = json.load(f)
    assert doc["version"] == 2 and doc["findings"]

    again = run_check([fixture], baseline=str(bl), default_excludes=False)
    assert again.findings == []
    assert len(again.baselined) == len(report.findings)


def test_baseline_fingerprint_survives_line_shift(tmp_path):
    src = "import time\n\n\ndef timed():\n    t0 = time.time()\n    return t0\n"
    p = tmp_path / "mod.py"
    p.write_text(src)
    fp0 = {baseline_mod.fingerprint(f) for f in analyze_file(str(p))[0]}
    p.write_text("# a new header comment\n" + src)
    fp1 = {baseline_mod.fingerprint(f) for f in analyze_file(str(p))[0]}
    assert fp0 == fp1 and fp0


# ---------------------------------------------------------------------------
# Rule plumbing details.
# ---------------------------------------------------------------------------

def test_static_argnames_drift_in_assignment_form(tmp_path):
    active, _ = _check_source(tmp_path, """\
        import jax

        def _f(x, chunk):
            return x

        f_jit = jax.jit(_f, static_argnames=("chunky",))
    """)
    assert [f.code for f in active] == ["TBX004"]
    assert "chunky" in active[0].message


def test_repo_declares_dp_tp_sp_axes():
    repo = RepoContext.discover([])
    assert {"dp", "tp", "sp"} <= set(repo.mesh_axes)


def test_traced_reachability_spans_helpers(tmp_path):
    # The helper is only reachable THROUGH the jitted caller; a host sync in
    # it must still be flagged, and a host sync in an unreachable function
    # must not.
    active, _ = _check_source(tmp_path, """\
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        def untraced(x):
            return np.asarray(x)

        @jax.jit
        def entry(x):
            return helper(x)
    """)
    assert [(f.code, f.line) for f in active] == [("TBX001", 5)]


def test_syntax_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    active, _ = analyze_file(str(p))
    assert [f.code for f in active] == ["TBX000"]


# ---------------------------------------------------------------------------
# Deep (jaxpr) mode.
# ---------------------------------------------------------------------------

def test_deep_mode_flags_decode_vocab_f32_and_traces_all_entries():
    from taboo_brittleness_tpu.analysis.deep import ENTRY_POINTS, run_deep

    findings = run_deep()
    # Registry drift (an entry failing to trace) must surface, not skip.
    assert not [f for f in findings if f.code == "TBX100"], [
        f.message for f in findings]
    by_entry = {f.path for f in findings if f.code == "TBX101"}
    # The decode's per-step [B, 1, V] f32 unembed is the known (reviewed,
    # baselined in tools/tbx_baseline.json) conversion deep mode must see.
    assert "<deep:runtime.decode.greedy_decode>" in by_entry
    assert len(ENTRY_POINTS) >= 3


def test_committed_deep_baseline_covers_current_deep_findings():
    from taboo_brittleness_tpu.analysis.deep import run_deep

    known = baseline_mod.load(os.path.join(REPO, "tools", "tbx_baseline.json"))
    new, _ = baseline_mod.split(run_deep(), known)
    assert new == [], [f.message for f in new]


# ---------------------------------------------------------------------------
# The repo-wide gate (the acceptance command, in-process and end-to-end).
# ---------------------------------------------------------------------------

def test_repo_is_clean_under_tbx_check():
    report = run_check(
        [os.path.join(REPO, d) for d in
         ("taboo_brittleness_tpu", "tools", "tests")])
    assert report.findings == [], "\n".join(f.format() for f in report.findings)
    # The corpus is excluded by default — prove the excludes did their job
    # rather than the corpus having gone stale.
    assert report.files_checked > 50


def test_cli_gate_exit_codes(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO}
    clean = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu.analysis",
         "taboo_brittleness_tpu", "tools", "tests"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    scratch = tmp_path / "scratch.py"
    scratch.write_text(
        "import time\n\n\ndef timed():\n    t0 = time.time()\n    return t0\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu.analysis", str(scratch)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert dirty.returncode == 1
    assert "TBX007" in dirty.stdout


def test_cli_list_rules():
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu.analysis",
         "--list-rules"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    for rule in RULES:
        assert rule.code in out.stdout
    assert "TBX101" in out.stdout


def test_every_rule_has_unique_code_and_alias():
    codes = [r.code for r in RULES]
    aliases = [r.alias for r in RULES]
    assert len(set(codes)) == len(codes) == 10
    assert len(set(aliases)) == len(aliases)
