"""Serving subsystem (taboo_brittleness_tpu/serve/, ISSUE 6).

Layers:

- engine: parity of the slot-stepped decode against the batched
  ``greedy_decode`` program, per-slot in-graph intervention switches, and
  the one-compiled-program contract (AOT registry: zero misses after
  warm-up);
- scheduler state machine: bounded-queue admission (rejection when full),
  slot recycle after EOS, mid-batch scenario switching, drain with
  in-flight sessions (zero dropped responses), and the ``serve.step``
  fault site (one poisoned session quarantines; the batch lives);
- serving-mode progress heartbeat + the supervisor's serve-aware wedge
  classifier (a healthy idle server is never wedged) and workload-
  conditional exit-1 handling (fake children, no jax in the child);
- the spool protocol (claim/recover/respond) and the loadgen selfcheck.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.obs.progress import ProgressReporter, read_progress
from taboo_brittleness_tpu.ops import sae as sae_ops
from taboo_brittleness_tpu.runtime import aot, chat, decode, resilience, supervise
from taboo_brittleness_tpu.runtime.resilience import FaultInjector, RetryPolicy
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer, target_token_id
from taboo_brittleness_tpu.serve.engine import EngineConfig, ServeEngine
from taboo_brittleness_tpu.serve.scheduler import (
    Request, Scenario, SlotScheduler, default_scenarios)

WORDS = ["ship", "moon", "hint", "clue", "secret", "word", "is", "My",
         "Give", "me", "a", "the", "about"]


@pytest.fixture(scope="module")
def tiny():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(7), cfg)
    tok = WordTokenizer(WORDS, vocab_size=cfg.vocab_size)
    sae = sae_ops.init_random(jax.random.PRNGKey(8), cfg.hidden_size, 64)
    return params, cfg, tok, sae


@pytest.fixture(autouse=True)
def _clean_state():
    supervise.reset_drain()
    resilience.set_injector(FaultInjector())
    yield
    supervise.reset_drain()
    resilience.set_injector(FaultInjector())


def make_engine(tiny, *, slots=3, stop_ids=(chat.EOS_ID, chat.END_OF_TURN_ID),
                with_sae=True, max_context=48, prompt_cols=24):
    params, cfg, tok, sae = tiny
    tap = 2
    return ServeEngine(
        params, cfg, tok,
        engine_config=EngineConfig(
            slots=slots, max_context=max_context, prompt_cols=prompt_cols,
            latent_slots=4, proj_rank=2,
            sae_layer=tap, proj_layer=tap, tap_layer=tap,
            stop_ids=stop_ids),
        sae=sae if with_sae else None)


def run_slot(engine, slot, prompt_ids, *, max_new, **admit_kw):
    """Drive ONE admitted slot to completion; returns its emitted tokens."""
    engine.admit(slot, prompt_ids, max_new=max_new, **admit_kw)
    toks = []
    for _ in range(200):
        out = engine.step()
        if bool(out.emitted[slot]):
            toks.append(int(out.tok[slot]))
        if bool(out.finished[slot]):
            engine.release(slot)
            return toks
    raise AssertionError("slot never finished")


# ---------------------------------------------------------------------------
# Engine.
# ---------------------------------------------------------------------------

def test_engine_matches_greedy_decode(tiny):
    """The slot-stepped serve decode (token-by-token prefill, per-row KV
    columns) reproduces the batched one-program greedy_decode exactly —
    same model, same greedy argmax, different program structure."""
    params, cfg, tok, _ = tiny
    prompt = "Give me a hint about the word"
    result, texts, ids = decode.generate(
        params, cfg, tok, [prompt], max_new_tokens=8)
    want = list(np.asarray(result.tokens)[0][:int(np.asarray(result.lengths)[0])])

    engine = make_engine(tiny)
    got = run_slot(engine, 0, ids[0], max_new=8)
    assert got == [int(t) for t in want]


def test_engine_forcing_prefill_matches_greedy_decode(tiny):
    """Token-forcing scenario: the opened model turn (prefill text) rides
    the same unified step; parity against generate(prefills=...)."""
    params, cfg, tok, _ = tiny
    prompt, prefill = "Give me a hint", "My secret word is"
    result, _, ids = decode.generate(
        params, cfg, tok, [prompt], prefills=[prefill], max_new_tokens=6)
    want = [int(t) for t in
            np.asarray(result.tokens)[0][:int(np.asarray(result.lengths)[0])]]

    engine = make_engine(tiny)
    got = run_slot(engine, 1, ids[0], max_new=6)
    assert got == want


def test_per_slot_intervention_switch(tiny):
    """Three concurrent sessions over the SAME prompt: two plain, one
    SAE-ablated — all through one program.  The plain slots agree exactly;
    the ablated slot's readout (and typically its tokens) diverge — the
    per-slot switch is real and slot-local."""
    params, cfg, tok, sae = tiny
    ids = tok.encode(chat.user_prompt("Give me a hint"))
    tgt = target_token_id(tok, "ship")
    # stop_ids=(-1,): fixed-length sessions so every slot emits max_new
    # tokens and the comparison is column-by-column.
    engine = make_engine(tiny, stop_ids=(-1,))
    n_new = 6
    engine.admit(0, ids, max_new=n_new, lens_target=tgt)
    engine.admit(1, ids, max_new=n_new, latent_ids=(0, 1, 2, 3),
                 lens_target=tgt)
    engine.admit(2, ids, max_new=n_new, lens_target=tgt)

    toks = {0: [], 1: [], 2: []}
    lens = {0: [], 1: [], 2: []}
    for _ in range(len(ids) + n_new + 2):
        out = engine.step()
        for s in toks:
            if bool(out.emitted[s]):
                toks[s].append(int(out.tok[s]))
                lens[s].append(float(out.lens_prob[s]))
        if all(bool(d) for d in np.asarray(engine.state.done)[:3]):
            break
    assert len(toks[0]) == len(toks[2]) == n_new
    assert toks[0] == toks[2]                      # plain slots identical
    assert lens[0] == pytest.approx(lens[2])
    # The ablation changed the residual at the tap layer, so the lens
    # readout over the SAME prompt must differ (the tokens usually do too,
    # but a tiny random model can tie on argmax — the readout cannot).
    assert lens[1] != pytest.approx(lens[0])


def test_engine_zero_aot_misses_after_warm_start(tiny):
    aot.reset()
    engine = make_engine(tiny)
    rec = engine.warm_start()
    assert rec["source"] in ("compiled", "memory", "disk")
    ids = engine.tok.encode(chat.user_prompt("Give me a hint"))
    run_slot(engine, 0, ids, max_new=4)
    run_slot(engine, 2, ids, max_new=4)            # recycle another slot
    st = aot.stats()["serve.step"]
    assert st["misses"] == 0 and st["fallbacks"] == 0
    assert st["hits"] >= 2


def test_engine_capacity_envelope(tiny):
    engine = make_engine(tiny, max_context=16, prompt_cols=8)
    assert engine.capacity_ok(8, 8)
    assert not engine.capacity_ok(9, 4)            # prompt too long
    assert not engine.capacity_ok(8, 9)            # context overflow
    with pytest.raises(ValueError):
        engine.admit(0, list(range(1, 10)), max_new=4)


# ---------------------------------------------------------------------------
# Scheduler state machine.
# ---------------------------------------------------------------------------

def _req(i, scenario, prompt="Give me a hint", seed=None):
    return Request(id=f"r{i:03d}", prompt=prompt, scenario=scenario,
                   seed=i if seed is None else seed)


def test_scheduler_admission_rejects_when_queue_full(tiny):
    engine = make_engine(tiny, slots=1, stop_ids=(-1,))
    sc = Scenario(name="chat", max_new_tokens=4)
    sched = SlotScheduler(engine, queue_limit=2)
    accepted = [sched.submit(_req(i, sc)) for i in range(6)]
    # 1 admitted straight into the slot; 2 queued; the rest rejected.
    assert accepted == [True, True, True, False, False, False]
    assert sched.rejected == 3
    resp = sched.run_until_idle()
    assert len(resp) == 3 and all(r.ok for r in resp)
    assert sched.completed == 3


def test_scheduler_recycles_slots_after_eos(tiny):
    """More sessions than slots: completion (EOS on the tiny model) frees
    the slot and the queue refills it — every accepted request resolves."""
    engine = make_engine(tiny, slots=2)
    sc = Scenario(name="chat", max_new_tokens=8)
    sched = SlotScheduler(engine, queue_limit=16)
    for i in range(7):
        assert sched.submit(_req(i, sc))
    resps = sched.run_until_idle()
    assert sorted(r.id for r in resps) == [f"r{i:03d}" for i in range(7)]
    assert all(r.ok for r in resps)
    assert sched.admitted == 7 and sched.completed == 7
    assert engine.free_slots() == [0, 1]           # all returned to the pool


def test_scheduler_switches_scenarios_mid_batch(tiny):
    """Slots re-admit with DIFFERENT scenarios while other sessions are in
    flight; the per-slot config switches with the slot, not the program."""
    engine = make_engine(tiny, slots=2, stop_ids=(-1,))
    tgt = target_token_id(engine.tok, "ship")
    scs = default_scenarios(max_new_tokens=4)
    sched = SlotScheduler(engine, queue_limit=16, lens_target_id=tgt)
    order = ["chat", "sae_ablate", "forcing", "chat_lens", "projection",
             "chat"]
    for i, name in enumerate(order):
        assert sched.submit(_req(i, scs[name]))
    resps = {r.id: r for r in sched.run_until_idle()}
    assert len(resps) == 6 and all(r.ok for r in resps.values())
    # Readout rode exactly the lens-enabled scenarios.
    assert resps["r001"].lens_probs and resps["r003"].lens_probs
    assert resps["r000"].lens_probs is None
    # Forcing prefill extends the prompt, not the generation.
    assert resps["r002"].steps > resps["r000"].steps


def test_scheduler_drain_with_in_flight_drops_nothing(tiny):
    """The SIGTERM contract at scheduler level: after drain(), new submits
    are rejected but every in-flight AND queued session completes."""
    engine = make_engine(tiny, slots=2, stop_ids=(-1,))
    sc = Scenario(name="chat", max_new_tokens=6)
    sched = SlotScheduler(engine, queue_limit=8)
    for i in range(5):
        assert sched.submit(_req(i, sc))
    sched.step()                                   # sessions genuinely in flight
    assert sched.in_flight == 2 and sched.queue_depth == 3
    sched.drain()
    assert not sched.submit(_req(99, sc))          # admission closed
    resps = sched.run_until_idle()
    assert sched.completed == 5                    # zero dropped
    assert sorted(r.id for r in resps) == [f"r{i:03d}" for i in range(5)]


def test_scheduler_quarantines_poisoned_session_not_batch(tiny):
    """A seeded serve.step fault matching ONE request id kills that session
    only: it resolves as quarantined, every other session completes."""
    inj = FaultInjector()
    inj.arm("serve.step", mode="fail", kind="permanent", times=1,
            match="poison")
    resilience.set_injector(inj)
    engine = make_engine(tiny, slots=3, stop_ids=(-1,))
    sc = Scenario(name="chat", max_new_tokens=5)
    sched = SlotScheduler(engine, queue_limit=8)
    assert sched.submit(Request(id="ok-1", prompt="Give me a hint", scenario=sc))
    assert sched.submit(Request(id="poison-1", prompt="Give me a hint", scenario=sc))
    assert sched.submit(Request(id="ok-2", prompt="Give me a hint", scenario=sc))
    resps = {r.id: r for r in sched.run_until_idle()}
    assert not resps["poison-1"].ok
    assert resps["poison-1"].finish == "quarantined"
    assert "InjectedPermanentFault" in resps["poison-1"].error
    assert resps["ok-1"].ok and resps["ok-2"].ok
    assert resps["ok-1"].steps == resps["ok-2"].steps > 0
    assert sched.quarantined == 1 and sched.completed == 2


def test_serve_quarantine_dumps_flightrec(tiny, tmp_path):
    """ISSUE 15 satellite: an injected ``serve.step`` quarantine freezes the
    flight-recorder ring to ``_flightrec.json`` — and the poisoned step is
    IN the frozen ring (recorded before the fault site fires)."""
    from taboo_brittleness_tpu.obs import flightrec

    flightrec.reset()
    flightrec.configure(str(tmp_path))
    try:
        inj = FaultInjector()
        inj.arm("serve.step", mode="fail", kind="permanent", times=1,
                match="poison")
        resilience.set_injector(inj)
        engine = make_engine(tiny, slots=2, stop_ids=(-1,))
        sc = Scenario(name="chat", max_new_tokens=4)
        sched = SlotScheduler(engine, queue_limit=4)
        sched.submit(Request(id="poison-1", prompt="Give me a hint",
                             scenario=sc))
        sched.submit(Request(id="ok-1", prompt="Give me a hint", scenario=sc))
        resps = {r.id: r for r in sched.run_until_idle()}
        assert not resps["poison-1"].ok and resps["ok-1"].ok

        path = os.path.join(str(tmp_path), "_flightrec.json")
        assert os.path.exists(path)
        with open(path) as f:
            data = json.load(f)
        assert data["reason"] == "serve.quarantine"
        assert data["context"]["request"] == "poison-1"
        steps = [r for r in data["ring"] if r["kind"] == "serve.step"]
        assert steps and any("poison-1" in r["requests"] for r in steps)
        assert data["ring"][-1]["kind"] == "serve.quarantine"
    finally:
        flightrec.reset()


def test_scheduler_fault_plan_via_env(tiny, monkeypatch):
    """The operator path: TABOO_FAULT_PLAN arms the serve.step site."""
    monkeypatch.setenv("TABOO_FAULT_PLAN", json.dumps(
        {"serve.step": {"mode": "fail", "kind": "permanent",
                        "times": 1, "match": "victim"}}))
    resilience.set_injector(None)                  # rebuild from env
    engine = make_engine(tiny, slots=2)
    sc = Scenario(name="chat", max_new_tokens=4)
    sched = SlotScheduler(engine, queue_limit=4)
    sched.submit(Request(id="victim", prompt="Give me a hint", scenario=sc))
    sched.submit(Request(id="bystander", prompt="Give me a hint", scenario=sc))
    resps = {r.id: r for r in sched.run_until_idle()}
    assert not resps["victim"].ok and resps["bystander"].ok


# ---------------------------------------------------------------------------
# Serving-mode progress + the supervisor's serve-aware classification.
# ---------------------------------------------------------------------------

def test_progress_serving_snapshot_fields(tmp_path):
    t = {"now": 100.0}
    rep = ProgressReporter(str(tmp_path / "_progress.json"), total_words=0,
                           interval=3600, clock=lambda: t["now"])
    rep.serving_update(in_flight=2, completed=5, queued=1, stepped=True)
    t["now"] = 104.5
    snap = rep.snapshot()
    assert snap["workload"] == "serve"
    assert snap["serving"]["in_flight"] == 2
    assert snap["serving"]["completed_requests"] == 5
    assert snap["serving"]["queued"] == 1
    assert snap["serving"]["last_step_age_seconds"] == pytest.approx(4.5)
    rep.write_now()
    on_disk = read_progress(rep.path)
    assert on_disk["workload"] == "serve"
    assert on_disk["serving"]["in_flight"] == 2


def test_live_latency_percentiles_in_progress(tiny, tmp_path):
    """ISSUE 7/15 satellites: per-scenario latency percentiles ride the
    serving heartbeat (``serving.latency``) with the WINDOWED view primary
    and the cumulative view labeled as such, stamped with ``window_s`` and
    per-view sample counts."""
    from taboo_brittleness_tpu.obs import metrics as obs_metrics

    obs_metrics.reset()        # per-scenario histograms are process-wide
    engine = make_engine(tiny, slots=2, stop_ids=(-1,))
    sc_chat = Scenario(name="chat", max_new_tokens=4)
    sc_lens = Scenario(name="chat_lens", lens_readout=True, max_new_tokens=4)
    sched = SlotScheduler(engine, queue_limit=8,
                          lens_target_id=target_token_id(engine.tok, "ship"))
    for i in range(3):
        assert sched.submit(_req(i, sc_chat))
    assert sched.submit(_req(3, sc_lens))
    sched.run_until_idle()

    pct = sched.latency_percentiles()
    assert pct["window_s"] > 0
    scen = pct["scenarios"]
    assert set(scen) == {"chat", "chat_lens"}
    assert scen["chat"]["cumulative"]["n"] == 3
    assert scen["chat_lens"]["cumulative"]["n"] == 1
    # No window has rolled yet, so the window view covers everything so far.
    assert scen["chat"]["window"]["n"] == 3
    for cell in scen.values():
        for view in ("window", "cumulative"):
            assert cell[view]["p50_s"] >= 0.0
            assert cell[view]["p99_s"] >= cell[view]["p50_s"]
            assert cell[view]["max_s"] >= cell[view]["p99_s"]

    rep = ProgressReporter(str(tmp_path / "_progress.json"), total_words=0,
                           interval=3600)
    rep.serving_update(in_flight=0, completed=4, latency=pct)
    rep.write_now()
    on_disk = read_progress(rep.path)
    disk_lat = on_disk["serving"]["latency"]
    assert disk_lat["window_s"] == pct["window_s"]
    assert disk_lat["scenarios"]["chat"]["cumulative"]["n"] == 3
    assert disk_lat["scenarios"]["chat_lens"]["window"]["p99_s"] >= 0.0
    # The last known percentiles persist across latency-less heartbeats
    # (the serve loop only recomputes them when requests resolve).
    rep.serving_update(in_flight=0, completed=5)
    snap = rep.snapshot()
    assert (snap["serving"]["latency"]["scenarios"]["chat"]["window"]["p50_s"]
            == scen["chat"]["window"]["p50_s"])
    assert snap["serving"]["completed_requests"] == 5


def _serve_progress(*, in_flight, last_step_age, pid=1234, stale=False):
    return {"status": "running", "pid": pid, "stale": stale,
            "workload": "serve", "age_seconds": 0.0,
            "serving": {"in_flight": in_flight,
                        "completed_requests": 3,
                        "last_step_age_seconds": last_step_age}}


def test_idle_server_is_never_wedged():
    """ISSUE 6 satellite: a healthy IDLE server (no sessions, no events for
    ages) must not be classified as pipeline-wedged by the supervisor."""
    p = _serve_progress(in_flight=0, last_step_age=9999.0)
    p["last_event_age_seconds"] = 9999.0           # would wedge a sweep
    assert supervise._wedge_reason(p, pid=1234, wedge_after=1.0) is None


def test_busy_server_with_stalled_steps_is_wedged():
    p = _serve_progress(in_flight=2, last_step_age=50.0)
    assert supervise._wedge_reason(p, pid=1234, wedge_after=1.0) == \
        "pipeline-wedged"
    fresh = _serve_progress(in_flight=2, last_step_age=0.01)
    assert supervise._wedge_reason(fresh, pid=1234, wedge_after=1.0) is None


def test_stale_heartbeat_still_wedges_a_server():
    p = _serve_progress(in_flight=0, last_step_age=0.0, stale=True)
    assert supervise._wedge_reason(p, pid=1234, wedge_after=1.0) == \
        "heartbeat-stale"


_WORKLOAD_CHILD = r"""
import json, os, sys, time

out, workload = sys.argv[1], sys.argv[2]
inc = os.environ.get("TBX_INCARNATION", "0")
payload = {"v": 1, "pid": os.getpid(), "updated_at": time.time(),
           "heartbeat_seconds": 0.05, "status": "running",
           "incarnation": int(inc)}
if workload == "serve":
    payload["workload"] = "serve"
    payload["serving"] = {"in_flight": 0, "completed_requests": 0,
                          "last_step_age_seconds": 0.0}
tmp = os.path.join(out, "_progress.json.tmp")
with open(tmp, "w") as f:
    json.dump(payload, f)
os.replace(tmp, os.path.join(out, "_progress.json"))
sys.exit(1 if inc == "0" or workload != "serve" else 0)
"""


def _run_workload_child(tmp_path, workload):
    out = str(tmp_path / "out")
    os.makedirs(out, exist_ok=True)
    child = str(tmp_path / "child.py")
    with open(child, "w") as f:
        f.write(_WORKLOAD_CHILD)
    return supervise.supervise(
        [sys.executable, child, out, workload], out,
        max_incarnations=3, poll_interval=0.02, grace=0.5, wedge_after=5.0,
        policy=RetryPolicy(max_retries=8, base_delay=0.0))


def test_supervise_serve_exit1_burns_incarnation(tmp_path):
    """ISSUE 6 satellite: a serving child's exit 1 is a crash loop, not
    'quarantine = completed' — the supervisor restarts it (and the second
    incarnation, exiting 0, completes the run)."""
    res = _run_workload_child(tmp_path, "serve")
    assert [r["outcome"] for r in res.incarnations] == ["crashed", "done"]
    assert res.incarnations[0]["reason"] == "serve-exit-1"
    assert res.exit_code == 0 and res.status == "done"


def test_supervise_sweep_exit1_still_passes_through(tmp_path):
    """The pre-existing sweep contract is untouched: exit 1 without a serve
    workload declaration passes through as quarantined-completed."""
    res = _run_workload_child(tmp_path, "sweep")
    assert [r["outcome"] for r in res.incarnations] == ["quarantined"]
    assert res.exit_code == 1 and res.status == "quarantined"


# ---------------------------------------------------------------------------
# Spool protocol + loadgen.
# ---------------------------------------------------------------------------

def test_spool_claim_recover_respond_roundtrip(tmp_path):
    from taboo_brittleness_tpu.serve.scheduler import Response
    from taboo_brittleness_tpu.serve.server import RequestSpool

    spool = RequestSpool(str(tmp_path))
    a = spool.put({"prompt": "hi", "scenario": "chat"})
    b = spool.put({"prompt": "yo", "scenario": "chat"})
    claimed = spool.claim(limit=10)
    assert sorted(p["id"] for p in claimed) == sorted([a, b])
    assert spool.claim(limit=10) == []             # claim is exclusive
    # Crash before responding: recover() re-surfaces both...
    assert sorted(p["id"] for p in spool.recover()) == sorted([a, b])
    # ...but an answered request stays recovered-free.
    spool.respond(Response(id=a, scenario="chat", ok=True, text="x"))
    assert [p["id"] for p in spool.recover()] == [b]
    assert spool.get_response(a)["ok"] is True
    assert spool.get_response(b) is None
    assert spool.completed_count() == 1


def test_spool_claim_respects_limit_and_torn_files(tmp_path):
    from taboo_brittleness_tpu.serve.server import RequestSpool

    spool = RequestSpool(str(tmp_path))
    for _ in range(3):
        spool.put({"prompt": "hi", "scenario": "chat"})
    with open(os.path.join(spool.requests_dir, "torn.json"), "w") as f:
        f.write('{"prompt": "tr')                  # mid-flight writer
    assert len(spool.claim(limit=2)) == 2
    assert len(spool.claim(limit=10)) == 1         # torn file skipped
    assert os.path.exists(os.path.join(spool.requests_dir, "torn.json"))


def test_loadgen_selfcheck(tiny):
    from taboo_brittleness_tpu.serve import loadgen

    report = loadgen.selfcheck(n_requests=16, seed=3)
    assert report["stage"] == "serve_latency"
    assert report["goodput"]["completed"] == 16
    for block in report["scenarios"].values():
        for key in loadgen.LATENCY_KEYS:
            assert key in block


def test_loadgen_schedule_is_seeded_deterministic():
    from taboo_brittleness_tpu.serve import loadgen

    scs = default_scenarios()
    mix = {name: 1.0 for name in scs}
    a = loadgen.build_schedule(12, seed=5, rate=10.0, mix=mix,
                               scenarios=scs, prompts=("p",))
    b = loadgen.build_schedule(12, seed=5, rate=10.0, mix=mix,
                               scenarios=scs, prompts=("p",))
    assert [(t, r.id, r.scenario.name) for t, r in a] == \
           [(t, r.id, r.scenario.name) for t, r in b]
    c = loadgen.build_schedule(12, seed=6, rate=10.0, mix=mix,
                               scenarios=scs, prompts=("p",))
    assert [(t, r.id) for t, r in a] != [(t, r.id) for t, r in c]
