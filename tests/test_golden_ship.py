"""ID-level golden test against the reference's committed real-model artifact
(`/root/reference/results/ll_topk_ship.json` — produced from the actual
`bcywinski/gemma-2-9b-it-taboo-ship` checkpoint) over the reference's committed
cache pairs (`src/data/processed/ship/prompt_01,02.npz`).

This is the last real-model oracle reachable without the 9B weights (VERDICT
round-2 item 5): it exercises the full cached-analysis path — response-start
detection, token→id mapping, ID-level current+previous zeroing, masked
positional sum, top-k — at true Gemma-2 vocab scale against numbers that came
out of the real model.

Gated on the one small asset this environment lacks: the Gemma-2 tokenizer.
Set ``TABOO_TOKENIZER_PATH`` to any directory containing the Gemma-2 tokenizer
files (e.g. a `google/gemma-2-9b-it` or `bcywinski/gemma-2-9b-it-taboo-*`
snapshot — see tools/fetch_and_convert.py) to enable.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from taboo_brittleness_tpu.ops import lens
from taboo_brittleness_tpu.runtime import cache as cache_io
from taboo_brittleness_tpu.runtime import chat
from taboo_brittleness_tpu.runtime.tokenizer import HFTokenizer, target_token_id

TOK_PATH = os.environ.get("TABOO_TOKENIZER_PATH")
REF = "/root/reference"
GOLD = os.path.join(REF, "results", "ll_topk_ship.json")

pytestmark = pytest.mark.skipif(
    not (TOK_PATH and os.path.exists(GOLD)),
    reason="set TABOO_TOKENIZER_PATH to a Gemma-2 tokenizer directory "
           "(the single asset needed; see tools/fetch_and_convert.py)")


@pytest.fixture(scope="module")
def tok():
    return HFTokenizer.from_pretrained(TOK_PATH)


@pytest.fixture(scope="module")
def gold():
    with open(GOLD) as f:
        return json.load(f)


def test_secret_id_space_mode(tok, gold):
    """' ship' tokenizes to the artifact's secret_id (7509) — the same gate
    tools/fetch_and_convert.py applies to a real checkpoint's tokenizer."""
    assert target_token_id(tok, gold["secret_str"]) == gold["secret_id"]


@pytest.mark.parametrize("p_idx", [0, 1])
def test_id_level_topk_matches_real_model_artifact(tok, gold, p_idx):
    """Top-10 ids from ID-level aggregation over the committed ship cache must
    equal the artifact's guesses_by_prompt row for that prompt."""
    npz, js = cache_io.pair_paths(
        os.path.join(REF, "src", "data", "processed"), "ship", p_idx)
    pair = cache_io.load_pair(npz, js, layer_idx=gold["layer"])

    ids = np.asarray(tok.convert_tokens_to_ids(pair.input_words), np.int32)
    start = chat.find_model_response_start(pair.input_words)
    T = len(pair.input_words)
    resp = np.arange(T) >= start

    top_ids, _ = lens.aggregate_masked_sum(
        jnp.asarray(pair.all_probs[gold["layer"]]),
        jnp.asarray(ids), jnp.asarray(resp), top_k=gold["k"])
    got = [int(i) for i in np.asarray(top_ids)]
    want = gold["guesses_by_prompt"][p_idx]
    assert got == want, (
        f"prompt {p_idx + 1}: ID-level top-{gold['k']} diverges from the "
        f"real-model artifact\n got: {got}\nwant: {want}")


def test_secret_in_top10_matches_passk(tok, gold):
    """The artifact's pass@10 (0.8) counts prompts whose top-10 contains the
    secret id; the two committed pairs are both hits — verify our aggregation
    reproduces that membership."""
    for p_idx in (0, 1):
        npz, js = cache_io.pair_paths(
            os.path.join(REF, "src", "data", "processed"), "ship", p_idx)
        pair = cache_io.load_pair(npz, js, layer_idx=gold["layer"])
        ids = np.asarray(tok.convert_tokens_to_ids(pair.input_words), np.int32)
        start = chat.find_model_response_start(pair.input_words)
        resp = np.arange(len(ids)) >= start
        top_ids, _ = lens.aggregate_masked_sum(
            jnp.asarray(pair.all_probs[gold["layer"]]),
            jnp.asarray(ids), jnp.asarray(resp), top_k=gold["k"])
        assert (gold["secret_id"] in np.asarray(top_ids)) == (
            gold["secret_id"] in gold["guesses_by_prompt"][p_idx])
