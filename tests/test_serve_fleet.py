"""Replica-fleet serving acceptance (ISSUE 17): leased request ownership,
death→re-spool recovery, burn-rate admission routing.

The centerpiece is a REAL chaos e2e: 3 ``tbx serve --replica`` subprocesses
over one shared request spool and ≥24 mixed-scenario requests, with replica
``w1`` killed by a ``die`` fault mid-decode and replica ``w2`` wedged past
the supervisor's wedge threshold by a ``delay`` fault.  Every request must
be answered EXACTLY once (first-writer-wins — duplicate completions park in
``responses/_duplicates/``, they are counted, never merged), nothing on
disk may be ``.corrupt``, the failure ledger must carry the
lease-expiry→re-spool chains, and the merged ``_events.jsonl`` must stay
green under ``trace_report --check``.

Around it: burn-router unit tests (weighted steering off fabricated
``slo.burn.*`` heartbeats, typed all-burning shed, wait-don't-shed when no
replica is live, drain→re-spool of a dead replica's backlog), the
claimed-file GC satellite (a 100-request single-server run leaves zero
stale ``.claimed`` entries), the mid-run claimed-but-unanswered audit
warning, in-process fault-site drills for ``serve.claim`` /
``serve.lease_renew`` / ``serve.respond``, serve_fleet trace invariants,
and the ``serve_fleet_recovery`` bench_compare gate.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from taboo_brittleness_tpu.obs.progress import read_progress
from taboo_brittleness_tpu.runtime import resilience, supervise
from taboo_brittleness_tpu.runtime.fleet import holder_token
from taboo_brittleness_tpu.runtime.resilience import (
    InjectedFault, RetryPolicy)
from taboo_brittleness_tpu.serve.replica import (
    BurnRouter, ServeFleetResult, _shed, reroute_orphans, run_serve_fleet)
from taboo_brittleness_tpu.serve.scheduler import (
    REJECT_ALL_REPLICAS_BURNING)
from taboo_brittleness_tpu.serve.server import (
    CLAIMED_SUFFIX, RequestSpool, ServeLeaseKeeper)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402
import trace_report  # noqa: E402

MIX = ("chat", "sae_ablate", "forcing")


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())
    monkeypatch.delenv("TBX_WORKER_ID", raising=False)
    monkeypatch.delenv("TABOO_FAULT_PLAN", raising=False)
    yield
    supervise.reset_drain()
    resilience.set_injector(resilience.FaultInjector())


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["TBX_OBS_PROGRESS_S"] = "0.2"
    env["TBX_SUPERVISE_BACKOFF_S"] = "0"
    env.pop("TABOO_FAULT_PLAN", None)
    env.pop("TBX_INCARNATION", None)
    env.pop("TBX_WORKER_ID", None)
    return env


def _replica_argv(out, lease_s):
    def argv(wid):
        return [sys.executable, "-m", "taboo_brittleness_tpu", "serve",
                "--synthetic", "--output-dir", out, "--replica",
                "--slots", "4", "--queue-limit", "8",
                "--max-new-tokens", "4", "--poll", "0.05",
                "--lease", str(lease_s)]
    return argv


def _heartbeat(out, wid, *, status="running", age=0.0, fast=0.0,
               in_flight=0):
    """Fabricate the ``_progress.<wid>.json`` contract the router reads."""
    path = os.path.join(out, f"_progress.{wid}.json")
    payload = {
        "v": 1, "worker": wid, "status": status,
        # tbx: wallclock-ok — the heartbeat contract is epoch-stamped
        "updated_at": time.time() - age,
        "heartbeat_seconds": 0.2, "workload": "serve",
        "serving": {"in_flight": in_flight, "completed_requests": 0,
                    "queued": 0},
        "slo": {"serve_latency.chat":
                {"burn": fast, "fast": fast, "slow": fast,
                 "ok": fast < 1.0}},
    }
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def _no_corrupt(root):
    return [p for p in glob.glob(os.path.join(root, "**", "*.corrupt"),
                                 recursive=True)]


# ---------------------------------------------------------------------------
# The chaos acceptance e2e.
# ---------------------------------------------------------------------------


def test_serve_fleet_chaos_e2e(tmp_path, monkeypatch):
    """3 replicas, 24 mixed requests fed once the fleet is up; w1 die'd
    mid-decode, w2 wedged past the supervisor's wedge threshold → every
    request answered exactly once through the lease-expiry→re-spool path,
    zero corruption, ledger chains, trace gate green."""
    out = str(tmp_path / "fleet")
    n_requests, lease_s = 24, 2.5
    # Both faults ride serve.step (fired per decode step with the worker in
    # context): the FIRST matching spec wins, so the w1/w2 specs are
    # independent.  die = replica SIGKILL mid-decode; the long delay wedges
    # w2 (its heartbeat thread stays fresh, decode stops) until the
    # supervisor kills it at wedge_after.
    plan = {"serve.step": [
        {"mode": "die", "times": 1, "match": "w1", "incarnation": 0},
        {"mode": "delay", "delay": 30.0, "times": 1, "match": "w2",
         "incarnation": 0},
    ]}
    for k, v in _env().items():
        monkeypatch.setenv(k, v)
    spool = RequestSpool(out, fleet=True)

    def _feed():
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            views = [read_progress(
                os.path.join(out, f"_progress.w{i}.json"), missing_ok=True)
                for i in range(3)]
            if all(v.get("status") == "running" for v in views):
                break
            time.sleep(0.1)
        for i in range(n_requests):
            spool.put({"id": f"e2e{i:03d}",
                       "prompt": "Give me a hint about the word",
                       "scenario": MIX[i % len(MIX)], "seed": i})

    threading.Thread(target=_feed, daemon=True).start()
    res = run_serve_fleet(
        out, replica_argv=_replica_argv(out, lease_s), n_replicas=3,
        replica_env={"JAX_PLATFORMS": "cpu",
                     "TABOO_FAULT_PLAN": json.dumps(plan),
                     "TBX_OBS_PROGRESS_S": "0.2",
                     "TBX_SUPERVISE_BACKOFF_S": "0"},
        lease_s=lease_s, poll_s=0.2, max_requests=n_requests,
        max_wall_s=300.0, max_incarnations=4, supervise_poll=0.2,
        grace=2.0, wedge_after=4.0,
        policy=RetryPolicy(max_retries=6, base_delay=0.0))

    assert res.status == "done" and res.exit_code == 0, res.to_dict()
    # Exactly once: one response file per request, duplicates PARKED (and
    # counted), never merged into responses/.
    rids = [f"e2e{i:03d}" for i in range(n_requests)]
    for rid in rids:
        assert spool.get_response(rid) is not None, f"{rid} unanswered"
    n_responses = sum(1 for n in os.listdir(spool.responses_dir)
                      if n.endswith(".json"))
    assert n_responses == n_requests
    assert res.duplicate_commits == spool.duplicate_count()
    assert res.duplicate_commits >= 0

    # Recovery went through the lease path, and both chaos victims burned
    # an incarnation (w1 died, w2 was wedge-killed).
    assert res.lease_expiries >= 1 and res.respooled >= 1, res.to_dict()
    assert res.recovery_seconds is not None
    incs = {r["worker_id"]: r["incarnations"] for r in res.replicas}
    assert incs["w1"] >= 2, f"w1 was never killed+relaunched: {incs}"
    assert incs["w2"] >= 2, f"w2 was never wedge-killed: {incs}"

    # Ledger carries the lease-expiry→re-spool chains.
    assert res.reissue_chains, "no re-spool chains recorded"
    with open(os.path.join(out, "_failures.json")) as f:
        ledger = json.load(f)
    assert ledger, "merged _failures.json empty"

    assert _no_corrupt(out) == []
    # No stale intake tombstones or claim markers survive a clean finish.
    spool.gc_claimed(force=True)
    assert spool.claimed_unanswered() == []

    # The merged event stream is green under the drift gate (which now
    # includes the serve_fleet invariants).
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         "--check", os.path.join(out, "_events.jsonl")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# Burn-rate router units.
# ---------------------------------------------------------------------------


def test_router_burn_weighted_steering(tmp_path):
    """A fast-burning replica gets measurably less admission weight: at
    fast=1.5 under cap 2.0 its weight is 0.25 vs the healthy replica's
    1.0, so over 400 seeded picks it receives well under half the healthy
    replica's share."""
    out = str(tmp_path)
    _heartbeat(out, "w0", fast=0.0)
    _heartbeat(out, "w1", fast=1.5)
    router = BurnRouter(out, ["w0", "w1"], burn_cap=2.0, seed=1)
    view = router.view()
    assert view["w0"]["weight"] == 1.0
    assert view["w1"]["weight"] == 0.25
    assert not view["w1"]["burning"]
    for _ in range(400):
        assert router.pick(view) in ("w0", "w1")
    assert router.routed["w1"] < 0.5 * router.routed["w0"], router.routed
    assert router.routed["w1"] > 0, "burning-but-under-cap must not starve"


def test_router_all_burning_sheds_typed(tmp_path):
    """Every live replica past the cap → no pick, and the coordinator's
    shed writes a typed ``all-replicas-burning`` rejection response."""
    out = str(tmp_path)
    _heartbeat(out, "w0", fast=2.5)
    _heartbeat(out, "w1", fast=3.0)
    router = BurnRouter(out, ["w0", "w1"], burn_cap=2.0, seed=0)
    view = router.view()
    assert BurnRouter.any_alive(view)
    assert BurnRouter.all_burning(view)
    assert all(v["burning"] for v in view.values())
    assert router.pick(view) is None

    spool = RequestSpool(out, fleet=True)
    rid = spool.put({"id": "shed0", "prompt": "p", "scenario": "chat"})
    payload = spool.route_intake(rid)
    _shed(spool, rid, payload)
    resp = spool.get_response(rid)
    assert resp is not None and resp["ok"] is False
    assert resp["reject_reason"] == REJECT_ALL_REPLICAS_BURNING
    assert resp["finish"] == "rejected"


def test_router_waits_when_no_replica_alive(tmp_path):
    """Stale or absent heartbeats mean startup / rolling restart, NOT
    overload: nothing is alive, nothing burns, intake must wait."""
    out = str(tmp_path)
    _heartbeat(out, "w0", age=60.0)           # stale: presumed dead
    _heartbeat(out, "w1", status="done")      # exited
    router = BurnRouter(out, ["w0", "w1", "w2"], burn_cap=2.0)
    view = router.view()
    assert not BurnRouter.any_alive(view)
    assert not BurnRouter.all_burning(view)
    assert router.pick(view) is None
    assert view["w2"]["alive"] is False       # no heartbeat at all


def test_reroute_orphans_moves_dead_replicas_backlog(tmp_path):
    """Drain→re-spool: a permanently-dead replica's unclaimed assignments
    move to a live replica, excluding the dead one as a target."""
    out = str(tmp_path)
    spool = RequestSpool(out, fleet=True)
    _heartbeat(out, "w0", fast=0.0)
    for i in range(3):
        spool.assign(f"q{i}", {"id": f"q{i}", "prompt": "p",
                               "scenario": "chat"}, "w1", attempt=1,
                     excluded=("w1-i0",))
    router = BurnRouter(out, ["w0", "w1"], burn_cap=2.0, seed=0)
    moved = reroute_orphans(spool, router, "w1")
    assert moved == 3
    assert spool.assigned_entries("w1") == []
    entries = spool.assigned_entries("w0")
    assert sorted(e["id"] for e in entries) == ["q0", "q1", "q2"]
    # Attempt counts and holder exclusions survive the move.
    assert all(e["attempt"] == 1 and e["excluded"] == ["w1-i0"]
               for e in entries)


# ---------------------------------------------------------------------------
# Claimed-file GC + the recover() blind-spot audit (satellites).
# ---------------------------------------------------------------------------


def test_claimed_gc_leaves_zero_stale_entries_after_100_requests(tmp_path):
    """The RequestSpool claimed-file leak fix: a 100-request single-server
    run leaves ZERO stale ``.claimed`` tombstones behind."""
    out = str(tmp_path / "serve")
    spool = RequestSpool(out)
    for i in range(100):
        spool.put({"id": f"gc{i:03d}", "prompt": "hint",
                   "scenario": MIX[i % len(MIX)]})
    proc = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu", "serve",
         "--synthetic", "--output-dir", out, "--slots", "8",
         "--queue-limit", "128", "--max-new-tokens", "2",
         "--poll", "0.02", "--max-requests", "100"],
        env=_env(), capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert spool.completed_count() == 100
    stale = [n for n in os.listdir(spool.requests_dir)
             if n.endswith(CLAIMED_SUFFIX)]
    assert stale == [], f"stale .claimed tombstones: {stale}"


def test_gc_claimed_removes_only_resolved_claims(tmp_path):
    spool = RequestSpool(str(tmp_path))
    r1 = spool.put({"id": "a1", "prompt": "p", "scenario": "chat"})
    r2 = spool.put({"id": "a2", "prompt": "p", "scenario": "chat"})
    for rid in (r1, r2):
        path = os.path.join(spool.requests_dir, f"{rid}.json")
        os.replace(path, path + CLAIMED_SUFFIX)
    # Only a1 has a response: GC must remove exactly its tombstone.
    with open(spool.response_path("a1"), "w") as f:
        json.dump({"id": "a1", "ok": True}, f)
    assert spool.gc_claimed(force=True) == 1
    left = [n for n in os.listdir(spool.requests_dir)
            if n.endswith(CLAIMED_SUFFIX)]
    assert left == [f"a2.json{CLAIMED_SUFFIX}"]
    # Throttled call (not forced, within the interval) reports None.
    assert spool.gc_claimed() is None
    assert spool.claimed_unanswered() == ["a2"]


def test_midrun_claimed_unanswered_emits_audit_warning(tmp_path):
    """The recover() blind spot: a claimed-but-unanswered file appearing
    MID-RUN (not at startup) must be surfaced with an obs warning."""
    out = str(tmp_path / "serve")
    spool = RequestSpool(out)
    proc = subprocess.Popen(
        [sys.executable, "-m", "taboo_brittleness_tpu", "serve",
         "--synthetic", "--output-dir", out, "--slots", "2",
         "--max-new-tokens", "2", "--poll", "0.02"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        # Wait until a real request is ANSWERED: only then is the server
        # past warm-up and startup recovery (which would legitimately adopt
        # a claimed file instead of flagging it) and into its main loop.
        spool.put({"id": "warmup", "prompt": "p", "scenario": "chat"})
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if spool.get_response("warmup") is not None:
                break
            time.sleep(0.1)
        assert spool.get_response("warmup") is not None, "server never up"
        # An orphaned claim the scheduler knows nothing about — the
        # signature a concurrent writer's crash leaves behind.
        with open(os.path.join(spool.requests_dir,
                               f"orphan.json{CLAIMED_SUFFIX}"), "w") as f:
            json.dump({"id": "orphan", "prompt": "p", "scenario": "chat"},
                      f)
        events_path = os.path.join(out, "_events.jsonl")
        warned = []
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not warned:
            time.sleep(0.3)
            try:
                with open(events_path) as f:
                    warned = [json.loads(ln) for ln in f
                              if '"serve.claimed_unanswered"' in ln]
            except (OSError, ValueError):
                warned = []
        assert warned, "no serve.claimed_unanswered warning emitted"
        assert warned[0]["attrs"]["request"] == "orphan"
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    assert proc.returncode == supervise.EXIT_DRAINED
    # The audit warns ONCE per orphan, not once per poll.
    with open(os.path.join(out, "_events.jsonl")) as f:
        n_warn = sum(1 for ln in f if '"serve.claimed_unanswered"' in ln)
    assert n_warn == 1


# ---------------------------------------------------------------------------
# Fault-site drills (in-process): serve.claim / serve.lease_renew /
# serve.respond.
# ---------------------------------------------------------------------------


def test_fault_site_serve_claim_is_retried_next_poll(tmp_path):
    """A transient fault at serve.claim loses the attempt, not the
    request: the next poll claims it."""
    spool = RequestSpool(str(tmp_path), fleet=True)
    spool.assign("c0", {"id": "c0", "prompt": "p", "scenario": "chat"},
                 "w0")
    inj = resilience.FaultInjector()
    inj.arm("serve.claim", mode="fail", times=1)
    resilience.set_injector(inj)
    with pytest.raises(InjectedFault):
        spool.claim_assigned("w0", holder_token("w0"), 4)
    claimed = spool.claim_assigned("w0", holder_token("w0"), 4)
    assert [c["id"] for c in claimed] == ["c0"]
    assert spool.assigned_entries("w0") == []


def test_fault_site_serve_lease_renew_lets_lease_expire(tmp_path):
    """Failed renewals (the keeper fails open) leave the lease to expire —
    exactly what the coordinator's re-spool scan keys on."""
    spool = RequestSpool(str(tmp_path), fleet=True)
    holder = holder_token("w0")
    inj = resilience.FaultInjector()
    inj.arm("serve.lease_renew", mode="fail", times=100)
    resilience.set_injector(inj)
    keeper = ServeLeaseKeeper(spool.lease_store, holder=holder,
                              worker="w0", lease_s=0.5).start()
    try:
        keeper.add("r0", 0)
        time.sleep(1.2)
        recs = spool.lease_store.leases()
        assert len(recs) == 1
        # tbx: wallclock-ok — comparing against the on-disk lease deadline
        assert recs[0]["expires_at"] < time.time(), (
            "lease was renewed despite the injected renewal faults")
    finally:
        keeper.stop()


def test_fault_site_serve_respond_and_first_writer_wins(tmp_path):
    from taboo_brittleness_tpu.serve.scheduler import Response

    spool = RequestSpool(str(tmp_path), fleet=True)
    resp = Response(id="r0", scenario="chat", ok=True, text="x")
    inj = resilience.FaultInjector()
    inj.arm("serve.respond", mode="fail", times=1)
    resilience.set_injector(inj)
    with pytest.raises(InjectedFault):
        spool.respond_exclusive(resp, holder=holder_token("w0"))
    # The fault fired BEFORE the link: nothing landed, a retry wins.
    assert spool.get_response("r0") is None
    assert spool.respond_exclusive(resp, holder=holder_token("w0")) is True
    # A raced duplicate from another holder loses benignly and is parked.
    dup = Response(id="r0", scenario="chat", ok=True, text="y")
    assert spool.respond_exclusive(dup, holder=holder_token("w1")) is False
    assert spool.get_response("r0")["text"] == "x"
    assert spool.duplicate_count() == 1


# ---------------------------------------------------------------------------
# trace_report: the serve_fleet invariants.
# ---------------------------------------------------------------------------


def _serve_fleet_stream(tmp_path, points):
    path = str(tmp_path / "_events.jsonl")
    seq = 0
    lines = []

    def add(rec):
        nonlocal seq
        seq += 1
        lines.append(json.dumps({"v": 1, "seq": seq, "t": float(seq),
                                 **rec}))

    add({"ev": "start", "kind": "run", "name": "sweep", "id": 1,
         "attrs": {"pipeline": "serve-fleet"}})
    for name, attrs in points:
        add({"ev": "point", "kind": "point", "name": name, "parent": 1,
             "attrs": attrs})
    add({"ev": "end", "kind": "run", "name": "sweep", "id": 1, "dur": 1.0,
         "status": "ok"})
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def test_check_serve_fleet_flags_double_answer(tmp_path):
    path = _serve_fleet_stream(tmp_path, [
        ("serve_fleet.route", {"request": "r0", "worker": "w0"}),
        ("serve.respond", {"request": "r0", "duplicate": False}),
        ("serve.respond", {"request": "r0", "duplicate": False}),
        ("serve_fleet.exit", {"status": "done"}),
    ])
    errors = trace_report.check_serve_fleet(
        path, list(trace_report.iter_events(path)))
    assert any("first-writer-wins violated" in e for e in errors)


def test_check_serve_fleet_flags_unresolved_expiry(tmp_path):
    path = _serve_fleet_stream(tmp_path, [
        ("serve_fleet.route", {"request": "r0", "worker": "w0"}),
        ("serve_fleet.lease_expired", {"request": "r0",
                                       "holder": "w0-i0"}),
        ("serve_fleet.exit", {"status": "done"}),
    ])
    errors = trace_report.check_serve_fleet(
        path, list(trace_report.iter_events(path)))
    assert any("never re-spooled" in e for e in errors)
    assert any("never answered" in e for e in errors)


def test_check_serve_fleet_clean_chain_passes(tmp_path):
    path = _serve_fleet_stream(tmp_path, [
        ("serve_fleet.route", {"request": "r0", "worker": "w0"}),
        ("serve_fleet.lease_expired", {"request": "r0",
                                       "holder": "w0-i0"}),
        ("serve_fleet.respool", {"request": "r0", "worker": "w1"}),
        ("serve.respond", {"request": "r0", "duplicate": False}),
        ("serve.respond", {"request": "r0", "duplicate": True}),
        ("serve_fleet.shed", {"request": "r1",
                              "reason": "all-replicas-burning"}),
        ("serve_fleet.exit", {"status": "done"}),
    ])
    assert trace_report.check_serve_fleet(
        path, list(trace_report.iter_events(path))) == []


def test_check_serve_fleet_noop_on_plain_streams():
    path = os.path.join(REPO, "tests", "fixtures", "obs", "_events.jsonl")
    assert trace_report.check_serve_fleet(
        path, list(trace_report.iter_events(path))) == []


def test_committed_serve_fleet_fixture_is_green():
    fixture = os.path.join(REPO, "tests", "fixtures", "obs", "serve_fleet",
                           "_events.jsonl")
    assert os.path.exists(fixture), "serve_fleet fixture not committed"
    assert trace_report.main(["--check", fixture]) == 0


# ---------------------------------------------------------------------------
# bench_compare: the serve_fleet_recovery regression gate.
# ---------------------------------------------------------------------------


def _write_round(tmp_path, n, extra):
    payload = {"n": n, "parsed": {"value": 20.0, **extra}}
    with open(str(tmp_path / f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(payload, f)


def test_bench_compare_serve_fleet_recovery_within_band(tmp_path):
    _write_round(tmp_path, 1,
                 {"serve_fleet_recovery": {"recovery_seconds": 4.0}})
    _write_round(tmp_path, 2,
                 {"serve_fleet_recovery": {"recovery_seconds": 5.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0 and not regressions


def test_bench_compare_serve_fleet_recovery_flags_regression(tmp_path):
    _write_round(tmp_path, 1,
                 {"serve_fleet_recovery": {"recovery_seconds": 4.0}})
    _write_round(tmp_path, 2,
                 {"serve_fleet_recovery": {"recovery_seconds": 9.0}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("serve_fleet_recovery.recovery_seconds" in r
               for r in regressions)


def test_bench_compare_serve_fleet_recovery_missing_is_skipped(tmp_path):
    _write_round(tmp_path, 1,
                 {"serve_fleet_recovery": {"recovery_seconds": 4.0}})
    _write_round(tmp_path, 2, {})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0
    assert any("serve_fleet_recovery.recovery_seconds" in line
               and "skipped" in line for line in lines)


# ---------------------------------------------------------------------------
# ServeFleetResult shape.
# ---------------------------------------------------------------------------


def test_serve_fleet_result_duck_types_merge_ledgers():
    """merge_ledgers reads status / reissue_chains / lease_expiries /
    duplicate_commits off FleetResult; ServeFleetResult must keep those
    exact names so the serve fleet reuses the merger unchanged."""
    res = ServeFleetResult(
        status="done", exit_code=0, requests_total=2, completed=2, shed=0,
        respooled=1, lease_expiries=1, duplicate_commits=1,
        recovery_seconds=0.5, wall_seconds=1.0, replicas=[],
        reissue_chains={"r0": [{"reason": "lease-expired"}]}, router={})
    for attr in ("status", "reissue_chains", "lease_expiries",
                 "duplicate_commits"):
        assert hasattr(res, attr)
    d = res.to_dict()
    assert d["version"] == 1 and d["shed_rate"] == 0.0
    assert ServeFleetResult(**{**{f.name: getattr(res, f.name)
                                  for f in res.__dataclass_fields__.values()
                                  }, "shed": 1}).shed_rate == 0.5
