"""Tier-1 CPU tests for the telemetry subsystem (taboo_brittleness_tpu/obs).

Covers the obs contract end to end: span nesting and thread-safety, JSONL
round-trip plus fail-open behavior under a fault-injected sink write
(resilience site ``obs.event_write``), metrics registry snapshots, the
``_progress.json`` heartbeat and staleness detection, and
``tools/trace_report.py`` rendered over a synthetic sweep's events.
"""

import json
import os
import sys
import threading
import time

import pytest

from taboo_brittleness_tpu import obs
from taboo_brittleness_tpu.obs import memory as obs_memory
from taboo_brittleness_tpu.obs import metrics as obs_metrics
from taboo_brittleness_tpu.obs import progress as obs_progress
from taboo_brittleness_tpu.obs import trace as obs_trace
from taboo_brittleness_tpu.runtime import resilience
from taboo_brittleness_tpu.runtime.resilience import FaultInjector

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import trace_report  # noqa: E402

FIXTURE_EVENTS = os.path.join(
    os.path.dirname(__file__), "fixtures", "obs", "_events.jsonl")


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Each test gets a pristine injector, metrics registry, and tracer
    stack (obs state is process-wide by design)."""
    resilience.set_injector(FaultInjector())
    obs_metrics.reset()
    yield
    while obs_trace.get_tracer() is not None:
        obs_trace.deactivate(obs_trace.get_tracer())
    resilience.set_injector(FaultInjector())
    obs_metrics.reset()


def _read_events(path):
    return list(obs.iter_events(path))


# ---------------------------------------------------------------------------
# Spans: nesting, attributes, thread-safety.
# ---------------------------------------------------------------------------

def test_span_nesting_and_round_trip(tmp_path):
    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path, run_id="run0")
    try:
        with t.span("sweep", kind="run", pipeline="test") as run:
            with t.span("word", kind="word", word="ship") as w:
                with t.span("decode", kind="program", rows=4) as p:
                    p.set(aot="hit")
                t.event("aot.build", entry="decode")
            assert w.parent_id == run.span_id
    finally:
        obs.deactivate(t)

    events = _read_events(path)
    starts = [e for e in events if e["ev"] == "start"]
    ends = [e for e in events if e["ev"] == "end"]
    points = [e for e in events if e["ev"] == "point"]
    assert [e["name"] for e in starts] == ["sweep", "word", "decode"]
    # Ends are innermost-first; each end carries dur + ok status.
    assert [e["name"] for e in ends] == ["decode", "word", "sweep"]
    assert all(e["status"] == "ok" and e["dur"] >= 0 for e in ends)
    # Parentage chains run -> word -> program; the point event parents to
    # the word span active on its thread.
    by_name = {e["name"]: e for e in starts}
    assert by_name["word"]["parent"] == by_name["sweep"]["id"]
    assert by_name["decode"]["parent"] == by_name["word"]["id"]
    assert points[0]["parent"] == by_name["word"]["id"]
    # Late attributes ride the end event; seq is strictly increasing.
    decode_end = next(e for e in ends if e["name"] == "decode")
    assert decode_end["attrs"]["aot"] == "hit"
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # The run start carries the wall-clock anchor and run id.
    assert by_name["sweep"]["run_id"] == "run0"
    assert by_name["sweep"]["wall"] > 0


def test_span_error_status_and_idempotent_end(tmp_path):
    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path)
    try:
        with pytest.raises(ValueError):
            with t.span("word", kind="word", word="moon"):
                raise ValueError("boom")
        sp = t.span("explicit", kind="phase")
        sp.end()
        sp.end()  # idempotent: __exit__ after end() must not double-emit
    finally:
        obs.deactivate(t)
    events = _read_events(path)
    word_end = next(e for e in events
                    if e["ev"] == "end" and e["name"] == "word")
    assert word_end["status"] == "error"
    assert "ValueError: boom" in word_end["error"]
    assert sum(1 for e in events
               if e["ev"] == "end" and e["name"] == "explicit") == 1


def test_tracer_thread_safety(tmp_path):
    """Concurrent writers from many threads: every event lands as one whole
    JSON line, seq is gap-free, and per-thread parentage never crosses
    threads (a worker's span must not nest under another thread's)."""
    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path)
    n_threads, n_spans = 8, 25

    def worker(k):
        for i in range(n_spans):
            with t.span(f"w{k}", kind="phase", i=i) as sp:
                sp.event("tick", k=k)

    try:
        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        obs.deactivate(t)

    events = _read_events(path)
    # start+end+point per span iteration; nothing torn, nothing dropped.
    assert len(events) == n_threads * n_spans * 3
    assert t.dropped == 0
    seqs = sorted(e["seq"] for e in events)
    assert seqs == list(range(1, len(events) + 1))
    starts = {e["id"]: e for e in events if e["ev"] == "start"}
    for e in events:
        if e["ev"] == "start" and e.get("parent") is not None:
            # Parent (if any) must be a span of the same worker thread.
            assert starts[e["parent"]]["name"] == e["name"]


def test_module_level_api_is_noop_without_tracer(tmp_path):
    assert obs.get_tracer() is None
    sp = obs.span("anything")
    assert sp is obs.NULL_SPAN
    with sp:
        sp.set(x=1).event("nested")
    obs.event("orphan")  # must not raise
    assert obs.last_seq() is None


# ---------------------------------------------------------------------------
# Sink: atomicity/fail-open under fault injection, buffered flush, torn tail.
# ---------------------------------------------------------------------------

def test_event_write_fault_is_fail_open(tmp_path):
    """An injected fault at obs.event_write drops events, counts them, and
    never raises into the instrumented code path."""
    inj = FaultInjector()
    inj.arm("obs.event_write", times=2, kind="permanent")
    resilience.set_injector(inj)

    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path)
    try:
        for i in range(4):
            t.event(f"e{i}")  # first two hit the fault; never raises
    finally:
        obs.deactivate(t)

    events = _read_events(path)
    assert [e["name"] for e in events] == ["e2", "e3"]
    assert t.dropped == 2
    assert obs_metrics.counter("obs.events_dropped").value == 2


def test_sink_open_failure_keeps_span_timing(tmp_path):
    """An unwritable sink path degrades to a sink-less tracer: spans still
    time and nest, nothing raises."""
    bad = str(tmp_path / "not_a_dir_file")
    with open(bad, "w") as f:
        f.write("x")
    t = obs.activate(os.path.join(bad, "_events.jsonl"))
    try:
        with t.span("word", kind="word", word="ship") as sp:
            assert sp.span_id == 1
        assert t.last_seq() == 2  # start + end, counted despite no sink
    finally:
        obs.deactivate(t)


def test_buffered_events_flush_on_close_and_flush(tmp_path):
    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path)
    try:
        t.event("buffered")
        # Small event volume stays in the buffer until an explicit flush.
        assert os.path.getsize(path) == 0 if os.path.exists(path) else True
        t.flush()
        assert [e["name"] for e in _read_events(path)] == ["buffered"]
        t.event("second")
    finally:
        obs.deactivate(t)  # close() flushes the tail
    assert [e["name"] for e in _read_events(path)] == ["buffered", "second"]


def test_iter_events_skips_torn_tail_strict_raises(tmp_path):
    path = str(tmp_path / "_events.jsonl")
    lines = [json.dumps({"v": 1, "seq": 1, "t": 0.0, "ev": "point",
                         "name": "ok"})]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
        f.write('{"v": 1, "seq": 2, "t": 0.01, "ev": "po')  # killed mid-write
    assert [e["name"] for e in obs.iter_events(path)] == ["ok"]
    with pytest.raises(ValueError, match="unparseable"):
        list(obs.iter_events(path, strict=True))


# ---------------------------------------------------------------------------
# Metrics registry.
# ---------------------------------------------------------------------------

def test_metrics_snapshot_shapes():
    obs_metrics.counter("decode.launches").inc()
    obs_metrics.counter("decode.launches").inc(2)
    obs_metrics.gauge("aot.decode.hits").set(7)
    h = obs_metrics.histogram("word.seconds")
    for v in (1.0, 2.0, 3.0, 10.0):
        h.observe(v)

    snap = obs_metrics.snapshot()
    assert snap["counters"]["decode.launches"] == 3
    assert snap["gauges"]["aot.decode.hits"] == 7
    hist = snap["histograms"]["word.seconds"]
    assert hist["count"] == 4 and hist["sum"] == 16.0
    assert hist["min"] == 1.0 and hist["max"] == 10.0
    assert hist["p50"] in (2.0, 3.0)
    # JSON-serializable by construction (the manifest embeds it verbatim).
    json.dumps(snap)


def test_metrics_type_collision_raises_and_reset():
    obs_metrics.counter("x")
    with pytest.raises(TypeError):
        obs_metrics.gauge("x")
    obs_metrics.reset()
    obs_metrics.gauge("x")  # fine after reset


def test_histogram_reservoir_bounded_and_concurrent():
    h = obs_metrics.histogram("h")
    n = obs_metrics._RESERVOIR_CAP * 3

    def worker(base):
        for i in range(n // 4):
            h.observe(float(base + i))

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n
    assert len(h._sample) == obs_metrics._RESERVOIR_CAP
    assert h.quantile(0.5) is not None


def test_manifest_snapshots_metrics_and_events_path(tmp_path):
    from taboo_brittleness_tpu.runtime.manifest import RunManifest

    obs_metrics.counter("decode.launches").inc(5)
    path = str(tmp_path / "_events.jsonl")
    t = obs.activate(path)
    try:
        d = RunManifest(command="test").to_dict()
    finally:
        obs.deactivate(t)
    assert d["obs"]["schema_version"] == obs.SCHEMA_VERSION
    assert d["obs"]["events_path"] == path
    assert d["obs"]["metrics"]["counters"]["decode.launches"] == 5
    # The stamp survives observer deactivation (manifest saves post-sweep).
    d2 = RunManifest(command="test").to_dict()
    assert d2["obs"]["events_path"] == path


# ---------------------------------------------------------------------------
# Progress heartbeat + staleness.
# ---------------------------------------------------------------------------

def test_progress_reporter_lifecycle(tmp_path):
    path = str(tmp_path / "_progress.json")
    clock = {"t": 100.0}
    rep = obs_progress.ProgressReporter(
        path, total_words=4, run_id="r1", interval=3600,
        min_write_interval=0.0, clock=lambda: clock["t"])
    rep.write_now()

    rep.word_started("ship")
    rep.phase("decode")
    snap = rep.snapshot()
    assert snap["current_word"] == "ship" and snap["phase"] == "decode"
    assert snap["eta_seconds"] is None  # no completed word yet

    clock["t"] += 10.0
    rep.word_done("ship")
    rep.word_skipped("moon")     # resumed: counts done, not toward the EMA
    rep.word_quarantined("lake")
    snap = rep.snapshot()
    assert snap["words_done"] == 2
    assert snap["words_quarantined"] == 1
    assert snap["word_seconds_ema"] == 10.0
    assert snap["eta_seconds"] == 10.0   # 1 remaining x 10 s EMA

    rep.finish("done")
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["status"] == "done" and on_disk["current_word"] is None


def test_progress_heartbeat_thread_rewrites_file(tmp_path):
    path = str(tmp_path / "_progress.json")
    rep = obs_progress.ProgressReporter(
        path, total_words=2, interval=0.05, min_write_interval=0.0)
    with rep:
        rep.word_started("ship")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    if json.load(f).get("current_word") == "ship":
                        break
            except (OSError, ValueError):
                pass
            time.sleep(0.02)
        else:
            pytest.fail("heartbeat never wrote the current word")
    data = obs_progress.read_progress(path)
    assert data["status"] == "done"
    assert data["stale"] is False      # finished runs are never stale


def test_progress_staleness_detection(tmp_path):
    path = str(tmp_path / "_progress.json")
    # tbx: wallclock-ok — forging an old cross-process epoch timestamp, the
    # one clock read_progress is specified against
    stale_state = {"v": 1, "updated_at": time.time() - 1000.0,
                   "heartbeat_seconds": 5.0, "status": "running"}
    with open(path, "w") as f:
        json.dump(stale_state, f)
    data = obs_progress.read_progress(path)
    assert data["stale"] is True
    assert data["age_seconds"] >= 999.0
    # A custom threshold larger than the age flips it back.
    assert obs_progress.read_progress(path, stale_after=2000)["stale"] is False


def test_progress_reports_last_event_age(tmp_path):
    t = obs.activate(str(tmp_path / "_events.jsonl"))
    try:
        t.event("tick")
        rep = obs_progress.ProgressReporter(
            str(tmp_path / "_progress.json"), total_words=1,
            interval=3600, tracer=t)
        snap = rep.snapshot()
        assert 0.0 <= snap["last_event_age_seconds"] < 60.0
    finally:
        obs.deactivate(t)


# ---------------------------------------------------------------------------
# Memory sampling.
# ---------------------------------------------------------------------------

def test_memory_sample_host_fields():
    s = obs_memory.sample()
    assert s["rss_bytes"] is None or s["rss_bytes"] > 0
    assert isinstance(s["devices"], list)  # CPU backend: usually empty
    compact = obs_memory.sample(compact=True)
    json.dumps(compact)
    if compact.get("rss_mb") is not None:
        assert compact["rss_mb"] > 0


def test_memory_sampler_disabled_at_zero_hz(tmp_path):
    t = obs.activate(str(tmp_path / "_events.jsonl"))
    try:
        sampler = obs_memory.MemorySampler(t, hz=0)
        assert sampler.start()._thread is None
        sampler.stop()
    finally:
        obs.deactivate(t)


# ---------------------------------------------------------------------------
# sweep_observer + trace_report on a synthetic sweep.
# ---------------------------------------------------------------------------

def _synthetic_sweep(out_dir, words=("ship", "moon")):
    with obs.sweep_observer(str(out_dir), pipeline="synthetic",
                            words=list(words)) as ob:
        assert ob.active
        for word in words:
            with ob.word(word) as wsp:
                wsp.set(attempts=1)
                with ob.phase("checkpoint.load"):
                    pass
                with ob.phase("compute:mode"):
                    with obs.span("decode", kind="program", rows=2):
                        pass
                ob.event("aot.build", entry="decode")


def test_sweep_observer_writes_events_and_progress(tmp_path):
    _synthetic_sweep(tmp_path)
    events_path = str(tmp_path / obs.EVENTS_FILENAME)
    progress_path = str(tmp_path / obs.PROGRESS_FILENAME)
    assert os.path.exists(events_path) and os.path.exists(progress_path)

    events = _read_events(events_path)
    run_starts = [e for e in events
                  if e["ev"] == "start" and e["kind"] == "run"]
    assert len(run_starts) == 1
    assert run_starts[0]["attrs"]["pipeline"] == "synthetic"
    word_spans = [e for e in events
                  if e["ev"] == "start" and e["kind"] == "word"]
    assert [e["attrs"]["word"] for e in word_spans] == ["ship", "moon"]

    progress = obs.read_progress(progress_path)
    assert progress["status"] == "done"
    assert progress["words_done"] == 2 and progress["words_total"] == 2
    # Word durations reached the metrics registry.
    assert obs_metrics.snapshot()["histograms"]["word.seconds"]["count"] == 2
    # The synthetic stream passes the schema gate the fixture is held to.
    assert trace_report.check(events_path) == []


def test_sweep_observer_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TBX_OBS", "0")
    with obs.sweep_observer(str(tmp_path), pipeline="x", words=["w"]) as ob:
        assert not ob.active
        with ob.word("w") as sp:
            assert sp is obs.NULL_SPAN
    assert not os.path.exists(tmp_path / obs.EVENTS_FILENAME)


def test_sweep_observer_nested_reuses_outer_tracer(tmp_path):
    outer_dir = tmp_path / "outer"
    inner_dir = tmp_path / "inner"
    with obs.sweep_observer(str(outer_dir), pipeline="outer",
                            words=["a"]) as outer:
        _synthetic_sweep(inner_dir, words=("b",))
        assert obs.get_tracer() is outer.tracer
    # The nested sweep's events land in the OUTER sink; inner gets progress
    # only.
    outer_events = _read_events(str(outer_dir / obs.EVENTS_FILENAME))
    assert sum(1 for e in outer_events
               if e["ev"] == "start" and e["kind"] == "run") == 2
    assert not os.path.exists(inner_dir / obs.EVENTS_FILENAME)
    assert os.path.exists(inner_dir / obs.PROGRESS_FILENAME)


def test_trace_report_renders_synthetic_sweep(tmp_path, capsys):
    _synthetic_sweep(tmp_path)
    events_path = str(tmp_path / obs.EVENTS_FILENAME)
    rc = trace_report.main([events_path, "--roofline", "none"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "run: synthetic" in out
    # Per-word x per-phase table with gap column + critical-path block.
    for token in ("ship", "moon", "checkpoint.load", "compute:mode",
                  "gap", "critical path:", "dispatch gap"):
        assert token in out
    # Program summary pools the decode spans.
    assert "decode" in out and "programs:" in out


def test_trace_report_roofline_join(tmp_path, capsys):
    _synthetic_sweep(tmp_path)
    detail = tmp_path / "bench_detail.json"
    detail.write_text(json.dumps({
        "sweep": {"phase_roofline": {"phases": {
            "decode": {"ceiling_seconds": 0.5}}}}}))
    rc = trace_report.main([str(tmp_path / obs.EVENTS_FILENAME),
                            "--roofline", str(detail)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ratio_of_ceiling" in out and "ceiling_s" in out


def test_trace_report_check_catches_violations(tmp_path):
    path = str(tmp_path / "_events.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "seq": 1, "t": 0.0, "ev": "start",
                            "kind": "word", "name": "word", "id": 1}) + "\n")
        f.write(json.dumps({"v": 1, "seq": 1, "t": 0.1, "ev": "end",
                            "id": 2, "dur": 0.1, "status": "ok"}) + "\n")
    errors = trace_report.check(path)
    msgs = "\n".join(errors)
    assert "seq 1 not increasing" in msgs
    assert "unknown span id" in msgs
    assert "never ended" in msgs
    assert "no root run span" in msgs
    assert trace_report.main([path, "--check"]) == 1
    # And the committed fixture stays clean (the check.sh drift gate).
    assert trace_report.main([FIXTURE_EVENTS, "--check"]) == 0


def test_obs_warn_emits_event_and_stderr(tmp_path, capsys):
    t = obs.activate(str(tmp_path / "_events.jsonl"))
    try:
        obs.warn("[study] something soft-failed", name="study.warn", word="x")
    finally:
        obs.deactivate(t)
    events = _read_events(str(tmp_path / "_events.jsonl"))
    assert events[0]["name"] == "study.warn"
    assert events[0]["attrs"]["level"] == "warn"
    assert "soft-failed" in capsys.readouterr().err
