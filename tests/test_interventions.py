"""Intervention sweep mechanics on the tiny model: edits bite, controls don't,
measurements are well-formed (Execution Plan items (e)/(f))."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.config import (
    Config, ExperimentConfig, InterventionConfig, ModelConfig)
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.ops import sae as sae_ops
from taboo_brittleness_tpu.pipelines import interventions as iv
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(11), cfg)
    tok = WordTokenizer([WORD, "hint", "clue", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=5),
        intervention=InterventionConfig(
            budgets=(1, 2), random_trials=2, ranks=(1, 2), spike_top_k=2),
        word_plurals={WORD: [WORD, WORD + "s"]},
        prompts=["Give me a hint", "a clue"],
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), d_model=cfg.hidden_size,
                              d_sae=32)
    return params, cfg, tok, config, sae


def test_prepare_word_state_shapes(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    B = len(config.prompts)
    assert state.sequences.shape[0] == B
    assert state.residual.shape == (*state.sequences.shape, cfg.hidden_size)
    assert state.spike_pos.shape == (B, config.intervention.spike_top_k)
    assert 0.0 <= state.secret_prob <= 1.0
    # spikes are inside the response region
    for b in range(B):
        for p in state.spike_pos[b]:
            assert state.response_mask[b, p]
    # baseline NLL nonzero only where next token is response
    assert (state.baseline_nll >= 0).all()
    assert len(state.guesses) == B


def test_zero_latent_ablation_is_noop_arm(setup):
    """m=0 (all -1 ids) must leave generation and NLL unchanged — the identity
    control that validates the delta-patching edit."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    ep = {"sae": sae, "latent_ids": jnp.asarray([-1], jnp.int32),
          "layer": config.model.layer_idx}
    arm = iv.measure_arm(params, cfg, tok, config, state, iv.sae_ablation_edit, ep)
    assert arm.delta_nll == pytest.approx(0.0, abs=1e-4)
    assert arm.secret_prob == pytest.approx(state.secret_prob, abs=1e-5)
    assert arm.guesses == state.guesses


def test_ablation_sweep_structure(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    res = iv.run_ablation_sweep(params, cfg, tok, config, state, sae)
    assert set(res["budgets"]) == {"1", "2"}
    for m, block in res["budgets"].items():
        assert set(block) == {"targeted", "random_mean", "random"}
        assert len(block["random"]) == config.intervention.random_trials
        for key in ("secret_prob", "delta_nll", "leak_rate", "prompt_accuracy"):
            assert key in block["targeted"]
            assert key in block["random_mean"]


def test_projection_edit_changes_model_and_sweep_runs(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    res = iv.run_projection_sweep(params, cfg, tok, config, state)
    assert set(res["ranks"]) == {"1", "2"}
    # removing a rank-2 subspace of the actual residual stream must perturb NLL
    r2 = res["ranks"]["2"]["targeted"]
    assert abs(r2["delta_nll"]) > 0.0


def test_spike_masked_arm_differs_from_full_arm(setup):
    """config.intervention.spike_masked edits ONLY the baseline spike
    positions — a different experiment from the every-position edit (VERDICT
    round-1 item 7), so the two arms must measurably differ."""
    import dataclasses

    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)

    # A strong projection edit makes the difference visible on a tiny model.
    basis, _ = __import__("taboo_brittleness_tpu.ops.projection",
                          fromlist=["principal_subspace"]).principal_subspace(
        jnp.asarray(state.residual.reshape(-1, cfg.hidden_size)), rank=2)

    ep_full = {"basis": basis, "layer": config.model.layer_idx}
    full = iv.measure_arm(params, cfg, tok, config, state,
                          iv.projection_edit, ep_full)

    masked_cfg = dataclasses.replace(
        config, intervention=dataclasses.replace(
            config.intervention, spike_masked=True))
    extra = iv._spike_mask_extra(masked_cfg, state)
    assert "spike_positions" in extra
    ep_masked = {**ep_full, **extra}
    masked = iv.measure_arm(params, cfg, tok, config, state,
                            iv.projection_edit, ep_masked)

    # Full-position editing perturbs the continuation NLL strictly more than
    # spike-only editing; the two arms must not coincide.
    assert abs(masked.delta_nll) < abs(full.delta_nll)
    assert masked.delta_nll != pytest.approx(full.delta_nll, abs=1e-6)


def test_spike_masked_sweep_runs_and_differs(setup):
    import dataclasses

    params, cfg, tok, config, sae = setup
    masked_cfg = dataclasses.replace(
        config, intervention=dataclasses.replace(
            config.intervention, budgets=(2,), random_trials=1,
            spike_masked=True))
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    res_masked = iv.run_ablation_sweep(params, cfg, tok, masked_cfg, state, sae)
    res_full = iv.run_ablation_sweep(
        params, cfg, tok, dataclasses.replace(
            config, intervention=dataclasses.replace(
                config.intervention, budgets=(2,), random_trials=1)),
        state, sae)
    t_m = res_masked["budgets"]["2"]["targeted"]
    t_f = res_full["budgets"]["2"]["targeted"]
    # Same targeted latents, different edit footprint.
    assert t_m["delta_nll"] != pytest.approx(t_f["delta_nll"], abs=1e-9)


def test_residual_measure_response_slice_matches_full(setup):
    """resp_start (the response-column slice that cuts ~40% of the readout
    matmul) must not change any measurement: aggregates identical, tap_prob
    identical on the sliced window and zero before it."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    B, T = state.sequences.shape
    s = max(T - config.experiment.max_new_tokens - 1, 0)
    targets = np.full((B,), state.target_id, np.int32)

    full = iv._residual_measure(
        params, cfg, jnp.asarray(state.residual), jnp.asarray(state.sequences),
        jnp.asarray(state.response_mask.astype(bool)), jnp.asarray(targets),
        top_k=config.model.top_k, resp_start=0)
    sliced = iv._residual_measure(
        params, cfg, jnp.asarray(state.residual), jnp.asarray(state.sequences),
        jnp.asarray(state.response_mask.astype(bool)), jnp.asarray(targets),
        top_k=config.model.top_k, resp_start=s)

    np.testing.assert_array_equal(np.asarray(sliced["agg_ids"]),
                                  np.asarray(full["agg_ids"]))
    np.testing.assert_allclose(np.asarray(sliced["agg_probs"]),
                               np.asarray(full["agg_probs"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sliced["row_prob_sum"]),
                               np.asarray(full["row_prob_sum"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sliced["tap_prob"])[:, s:],
                               np.asarray(full["tap_prob"])[:, s:], rtol=1e-6)
    assert (np.asarray(sliced["tap_prob"])[:, :s] == 0).all()


def test_nll_response_slice_matches_full(setup):
    """The sliced NLL readout (XLA row-chunk path) must reproduce the
    unsliced baseline at every position (zeros outside the response window
    either way)."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    T = state.sequences.shape[1]
    s = max(T - config.experiment.max_new_tokens - 1, 0)
    next_mask = np.zeros_like(state.response_mask)
    next_mask[:, :-1] = state.response_mask[:, 1:]
    args = (params, cfg, jnp.asarray(state.sequences),
            jnp.asarray(state.valid.astype(bool)),
            jnp.asarray(state.positions), jnp.asarray(next_mask))

    base = np.asarray(iv._nll_jit(*args, resp_start=0))
    got = np.asarray(iv._nll_jit(*args, resp_start=s))
    np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-5)


def test_residual_measure_foldexp_matches_softmax(setup):
    """The readout-copy optimization (variant='foldexp', the production
    default) must agree with the byte-stable softmax schedule to float
    rounding: same math, different op order (see _residual_measure)."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    args = (params, cfg, jnp.asarray(state.residual),
            jnp.asarray(state.sequences), jnp.asarray(state.response_mask),
            jnp.full((state.sequences.shape[0],), state.target_id, jnp.int32))
    kw = dict(top_k=config.model.top_k, resp_start=state.resp_start)
    a = iv._residual_measure(*args, variant="softmax", **kw)
    b = iv._residual_measure(*args, variant="foldexp", **kw)
    np.testing.assert_allclose(np.asarray(a["tap_prob"]),
                               np.asarray(b["tap_prob"]), rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(a["row_prob_sum"]),
                               np.asarray(b["row_prob_sum"]), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(a["agg_probs"]),
                               np.asarray(b["agg_probs"]), rtol=2e-5, atol=1e-7)
    # Chunk size is a schedule knob, never a results knob.
    c = iv._residual_measure(*args, variant="foldexp", chunk=1, **kw)
    np.testing.assert_allclose(np.asarray(b["agg_probs"]),
                               np.asarray(c["agg_probs"]), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(b["agg_ids"]),
                                  np.asarray(c["agg_ids"]))
    with pytest.raises(ValueError, match="variant"):
        jax.eval_shape(lambda: iv._residual_measure(*args, variant="nope", **kw))


def test_latent_scoring_estimators(setup):
    """Both Execution-Plan scoring estimators run and differ; the sweep JSON
    records which one targeted the latents (VERDICT round-3 item 7)."""
    import dataclasses as dc

    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)

    corr = iv.score_latents_for_word(state, sae, params, config=config, cfg=cfg)
    cos_cfg = dc.replace(config, intervention=dc.replace(
        config.intervention, scoring="cosine"))
    cos = iv.score_latents_for_word(state, sae, params, config=cos_cfg, cfg=cfg)
    assert corr.shape == cos.shape == (sae.d_sae,)
    assert np.all(corr >= 0.0) and np.all(cos >= 0.0)  # max(0, rel) clamps
    # Different estimators -> different score vectors (rankings CAN differ).
    assert not np.allclose(corr, cos)
    # Deterministic: same inputs, same scores.
    np.testing.assert_array_equal(
        corr, iv.score_latents_for_word(state, sae, params, config=config,
                                        cfg=cfg))

    with pytest.raises(ValueError, match="unknown intervention.scoring"):
        bad = dc.replace(config, intervention=dc.replace(
            config.intervention, scoring="nope"))
        iv.score_latents_for_word(state, sae, params, config=bad, cfg=cfg)

    res = iv.run_ablation_sweep(params, cfg, tok, config, state, sae)
    assert res["scoring"] == "correlation"
    res_cos = iv.run_ablation_sweep(params, cfg, tok, cos_cfg, state, sae)
    assert res_cos["scoring"] == "cosine"


def test_latent_secret_correlation_matches_numpy(setup):
    """Weighted Pearson op vs a plain numpy oracle on the weighted subset."""
    from taboo_brittleness_tpu.ops.sae import latent_secret_correlation

    rng = np.random.default_rng(0)
    N, S = 40, 7
    acts = rng.normal(size=(N, S)).astype(np.float32)
    y = rng.normal(size=(N,)).astype(np.float32)
    w = (rng.random(N) > 0.3).astype(np.float32)
    got = np.asarray(latent_secret_correlation(
        jnp.asarray(acts), jnp.asarray(y), jnp.asarray(w)))
    sel = w > 0
    want = np.array([np.corrcoef(acts[sel, s], y[sel])[0, 1] for s in range(S)])
    np.testing.assert_allclose(got, want, atol=1e-4)
    # A latent that IS the secret logit correlates at +1; its negation at -1.
    acts2 = np.stack([y, -y], axis=1)
    got2 = np.asarray(latent_secret_correlation(
        jnp.asarray(acts2), jnp.asarray(y), jnp.ones(N, np.float32)))
    np.testing.assert_allclose(got2, [1.0, -1.0], atol=1e-4)


def test_latent_secret_correlation_stream_matches_dense(setup):
    """The streamed (encode-fused, chunked-moment) product path must agree
    with the dense oracle — including when N does not divide the chunk."""
    from taboo_brittleness_tpu.ops import sae as sae_ops

    params, cfg, tok, config, sae = setup
    rng = np.random.default_rng(1)
    N = 37                                    # does not divide chunk=8
    x = rng.normal(size=(N, cfg.hidden_size)).astype(np.float32)
    y = rng.normal(size=(N,)).astype(np.float32)
    w = (rng.random(N) > 0.25).astype(np.float32)
    dense = sae_ops.latent_secret_correlation(
        sae_ops.encode(sae, jnp.asarray(x)), jnp.asarray(y), jnp.asarray(w))
    stream = sae_ops.latent_secret_correlation_stream(
        sae, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w), chunk=8)
    np.testing.assert_allclose(np.asarray(stream), np.asarray(dense),
                               atol=2e-4)


def test_full_study_writes_json(setup, tmp_path):
    params, cfg, tok, config, sae = setup
    out = str(tmp_path / "study.json")
    res = iv.run_intervention_study(
        params, cfg, tok, config, WORD, sae, output_path=out)
    assert set(res) == {"word", "baseline", "ablation", "projection"}
    import json
    with open(out) as f:
        loaded = json.load(f)
    assert loaded["word"] == WORD


def test_study_json_schema_is_stable(setup, tmp_path):
    """Downstream analysis (plots, reports, cross-round comparisons) keys on
    this exact structure — a silent schema change would orphan every
    previously written study JSON, so pin it field by field."""
    params, cfg, tok, config, sae = setup
    res = iv.run_intervention_study(
        params, cfg, tok, config, WORD, sae,
        output_path=str(tmp_path / "s.json"))

    assert set(res["baseline"]) == {"secret_prob", "guesses", "response_texts"}
    assert set(res["ablation"]) == {"word", "scoring", "budgets"}
    assert res["ablation"]["scoring"] in ("correlation", "cosine")
    assert set(res["projection"]) == {"word", "ranks"}

    arm_keys = {"secret_prob", "secret_prob_drop", "delta_nll", "leak_rate",
                "prompt_accuracy", "any_pass", "guesses"}
    mean_keys = arm_keys - {"guesses"}
    for grid, key in ((res["ablation"]["budgets"], "budgets"),
                      (res["projection"]["ranks"], "ranks")):
        expected = {str(v) for v in getattr(config.intervention, key)}
        assert set(grid) == expected
        for cell in grid.values():
            assert set(cell) == {"targeted", "random_mean", "random"}
            assert set(cell["targeted"]) == arm_keys
            assert set(cell["random_mean"]) == mean_keys
            assert len(cell["random"]) == config.intervention.random_trials
            for r in cell["random"]:
                assert set(r) == arm_keys


# ---------------------------------------------------------------------------
# Round-3: one compiled program across arms/budgets; batched-arm parity.
# ---------------------------------------------------------------------------

_TRACES = {"n": 0}


def _counting_ablation_edit(h, idx, ep):
    """Module-level edit fn with a trace-time side effect: the counter bumps
    only when a program TRACES (not when the cached executable runs)."""
    _TRACES["n"] += 1
    return iv.sae_ablation_edit(h, idx, ep)


def test_measure_arms_one_trace_across_arm_values(setup):
    """Different arm VALUES with the same shapes must reuse the compiled
    decode/lens/NLL programs — zero new traces (VERDICT round-2 item 1)."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    shared = {"sae": sae, "layer": config.model.layer_idx}

    ids1 = np.asarray([[0, -1], [3, 7], [5, -1]], np.int32)
    _TRACES["n"] = 0
    arms1 = iv.measure_arms(params, cfg, tok, config, state,
                            _counting_ablation_edit, shared,
                            {"latent_ids": ids1})
    assert len(arms1) == 3
    first = _TRACES["n"]
    assert first > 0  # the programs really traced through the edit

    ids2 = np.asarray([[1, 2], [4, -1], [6, 8]], np.int32)
    arms2 = iv.measure_arms(params, cfg, tok, config, state,
                            _counting_ablation_edit, shared,
                            {"latent_ids": ids2})
    assert len(arms2) == 3
    assert _TRACES["n"] == first, "same shapes retraced"


def test_sweep_shares_one_program_across_budgets(setup):
    """A whole ablation sweep (all budgets x all arms) adds at most ONE cache
    entry per jitted program: budget id-lists are padded to the max budget so
    shapes never change (VERDICT round-2 items 1+2)."""
    from taboo_brittleness_tpu.runtime import decode as dec_mod

    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)

    before = (iv._residual_measure._cache_size(),
              iv._nll_jit._cache_size(),
              dec_mod.greedy_decode._cache_size())
    iv.run_ablation_sweep(params, cfg, tok, config, state, sae)  # budgets (1,2) R=2
    after = (iv._residual_measure._cache_size(),
             iv._nll_jit._cache_size(),
             dec_mod.greedy_decode._cache_size())
    deltas = tuple(a - b for a, b in zip(after, before))
    assert all(d <= 1 for d in deltas), f"per-budget retrace: {deltas}"

    # A second sweep with different random draws adds ZERO new entries.
    iv.run_ablation_sweep(params, cfg, tok, config, state, sae, seed=123)
    again = (iv._residual_measure._cache_size(),
             iv._nll_jit._cache_size(),
             dec_mod.greedy_decode._cache_size())
    assert again == after


def test_batched_arms_match_single_arm(setup):
    """Arms folded into the row axis must score identically to the one-arm
    path (padding with -1 ids is inert)."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    L = config.model.layer_idx

    single = iv.measure_arm(
        params, cfg, tok, config, state, iv.sae_ablation_edit,
        {"sae": sae, "latent_ids": jnp.asarray([3, 7], jnp.int32), "layer": L})

    arms = iv.measure_arms(
        params, cfg, tok, config, state, iv.sae_ablation_edit,
        {"sae": sae, "layer": L},
        {"latent_ids": np.asarray([[3, 7], [5, -1]], np.int32)})

    assert arms[0].guesses == single.guesses
    assert arms[0].secret_prob == pytest.approx(single.secret_prob, abs=1e-5)
    assert arms[0].delta_nll == pytest.approx(single.delta_nll, abs=1e-5)
    assert arms[0].leak_rate == single.leak_rate


def test_arm_chunking_matches_full_batch(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    shared = {"sae": sae, "layer": config.model.layer_idx}
    ids = np.asarray([[0, -1], [3, 7], [5, -1]], np.int32)

    full = iv.measure_arms(params, cfg, tok, config, state,
                           iv.sae_ablation_edit, shared, {"latent_ids": ids})
    before = iv._residual_measure._cache_size()
    chunked = iv.measure_arms(params, cfg, tok, config, state,
                              iv.sae_ablation_edit, shared,
                              {"latent_ids": ids}, arm_chunk=2)
    # 3 arms in chunks of 2 -> the ragged final chunk pads to 2 arms, so both
    # launches share ONE compiled program (and at most one new entry total).
    assert iv._residual_measure._cache_size() - before <= 1
    for f, c in zip(full, chunked):
        assert f.guesses == c.guesses
        assert f.secret_prob == pytest.approx(c.secret_prob, abs=1e-5)
        assert f.delta_nll == pytest.approx(c.delta_nll, abs=1e-5)


def test_per_row_latent_ablation_matches_shared(setup):
    """ops-level: [B, m] per-row ids reduce to the shared-[m] semantics when
    all rows carry the same ids."""
    params, cfg, tok, config, sae = setup
    x = jax.random.normal(jax.random.PRNGKey(5), (3, 4, cfg.hidden_size))
    ids = jnp.asarray([1, 9], jnp.int32)
    shared_out = sae_ops.ablate_latents(sae, x, ids)
    rows_out = sae_ops.ablate_latents(
        sae, x, jnp.broadcast_to(ids, (3, 2)))
    np.testing.assert_allclose(np.asarray(shared_out), np.asarray(rows_out),
                               rtol=1e-6)
    # and distinct rows actually differ
    mixed = sae_ops.ablate_latents(
        sae, x, jnp.asarray([[1, 9], [2, 4], [-1, -1]], jnp.int32))
    assert not np.allclose(np.asarray(mixed)[1], np.asarray(shared_out)[1])
    np.testing.assert_allclose(np.asarray(mixed)[2], np.asarray(x)[2],
                               rtol=1e-6)  # -1 rows are identity


def test_per_row_subspace_removal_matches_shared(setup):
    from taboo_brittleness_tpu.ops import projection as proj

    params, cfg, tok, config, sae = setup
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 3, cfg.hidden_size))
    u = proj.random_subspace(jax.random.PRNGKey(7), cfg.hidden_size, 2)
    shared_out = proj.remove_subspace(x, u)
    rows_out = proj.remove_subspace(
        x, jnp.broadcast_to(u, (2, *u.shape)))
    np.testing.assert_allclose(np.asarray(shared_out), np.asarray(rows_out),
                               rtol=1e-5, atol=1e-5)
    # zero-padded columns are inert (rank padding invariant)
    padded = jnp.pad(u, ((0, 0), (0, 3)))
    pad_out = proj.remove_subspace(x, padded)
    np.testing.assert_allclose(np.asarray(shared_out), np.asarray(pad_out),
                               rtol=1e-5, atol=1e-5)


def test_run_intervention_studies_resumable(setup, tmp_path):
    """Multi-word driver: skip-if-exists per word (crash/resume story), loader
    called once per uncached word."""
    import dataclasses as dc
    import json as json_mod

    params, cfg, tok, config, sae = setup
    fast = dc.replace(config, intervention=dc.replace(
        config.intervention, budgets=(1,), random_trials=1, ranks=(1,)))
    out_dir = str(tmp_path / "studies")
    loads = []

    def loader(word):
        loads.append(word)
        return params, cfg, tok

    res1 = iv.run_intervention_studies(
        fast, model_loader=loader, sae=sae, words=[WORD], output_dir=out_dir)
    assert loads == [WORD]
    path = f"{out_dir}/{WORD}.json"
    assert set(res1[WORD]) == {"word", "baseline", "ablation", "projection"}

    # Resume: nothing reloads, results come back from disk identically.
    res2 = iv.run_intervention_studies(
        fast, model_loader=loader, sae=sae, words=[WORD], output_dir=out_dir)
    assert loads == [WORD]
    with open(path) as f:
        assert res2[WORD] == json_mod.load(f)


def test_studies_never_prefetch_skipped_words(setup, tmp_path):
    """A word whose results already exist must not be prefetched: the loader
    would pin its params in the pending slot with nobody to consume them."""
    import dataclasses as dc

    params, cfg, tok, config, sae = setup
    fast = dc.replace(config, intervention=dc.replace(
        config.intervention, budgets=(1,), random_trials=1, ranks=(1,)))
    out_dir = tmp_path / "studies"
    out_dir.mkdir()
    # Pre-complete the SECOND word so only the first runs.
    (out_dir / "done_word.json").write_text('{"word": "done_word"}')

    prefetched = []

    class Loader:
        def __call__(self, word):
            return params, cfg, tok

        def prefetch(self, word):
            prefetched.append(word)

    res = iv.run_intervention_studies(
        fast, model_loader=Loader(), sae=sae, words=[WORD, "done_word"],
        output_dir=str(out_dir))
    assert prefetched == []                       # next word was done
    assert res["done_word"] == {"word": "done_word"}
    assert set(res[WORD]) == {"word", "baseline", "ablation", "projection"}


@pytest.mark.parametrize("spike_masked", [False, True])
def test_measure_arms_dp_mesh_matches_single_device(setup, spike_masked):
    """Rows sharded over the mesh's dp axis must score identically to the
    unsharded path — the sweep-grid data parallelism of SURVEY.md §2.3,
    reachable from the pipeline (not just the dryrun).  The spike_masked
    variant composes the full round-3 feature stack (per-prompt spike
    positions tiled across arms + batched arms + dp sharding)."""
    import dataclasses as dc

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from taboo_brittleness_tpu.config import MeshConfig
    from taboo_brittleness_tpu.parallel import mesh as meshlib

    params, cfg, tok, config, sae = setup
    if spike_masked:
        config = dc.replace(config, intervention=dc.replace(
            config.intervention, spike_masked=True))
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    shared = {"sae": sae, "layer": config.model.layer_idx,
              **iv._spike_mask_extra(config, state)}
    assert ("spike_positions" in shared) == spike_masked
    # 4 arms (rows of m=2 latent ids each) x 2 prompts = 8 rows -> dp=8 divides.
    ids = np.asarray([[0, -1], [3, 7], [5, -1], [2, 9]], np.int32)

    plain = iv.measure_arms(params, cfg, tok, config, state,
                            iv.sae_ablation_edit, shared, {"latent_ids": ids})
    m = meshlib.make_mesh(MeshConfig(dp=-1, tp=1, sp=1))
    sharded = iv.measure_arms(params, cfg, tok, config, state,
                              iv.sae_ablation_edit, shared,
                              {"latent_ids": ids}, mesh=m)
    for a, b in zip(plain, sharded):
        assert a.guesses == b.guesses
        assert a.secret_prob == pytest.approx(b.secret_prob, abs=1e-5)
        assert a.delta_nll == pytest.approx(b.delta_nll, abs=1e-5)


def test_dp_mesh_pads_non_dividing_rows(setup):
    """Rows that do NOT divide dp must still run sharded (padded to the dp
    multiple, pad rows stripped) with results identical to single-device —
    never a silent unsharded fallback (VERDICT round-3 item 6)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from taboo_brittleness_tpu.config import MeshConfig
    from taboo_brittleness_tpu.parallel import mesh as meshlib

    params, cfg, tok, config, sae = setup
    m = meshlib.make_mesh(MeshConfig(dp=-1, tp=1, sp=1))
    dp = m.shape["dp"]

    # The silent-fallback hole is closed at the source: non-dividing rows are
    # a hard error in _dp_sharding, so no caller can quietly run unsharded.
    assert iv._dp_sharding(m, 2, dp * 2) is not None
    with pytest.raises(ValueError, match="dp sharding is never dropped"):
        iv._dp_sharding(m, 2, dp * 2 + 1)

    # 3 arms x 2 prompts = 6 rows on a dp=8 mesh (6 % 8 != 0) -> pads to 8.
    state_plain = iv.prepare_word_state(params, cfg, tok, config, WORD)
    state_mesh = iv.prepare_word_state(params, cfg, tok, config, WORD, mesh=m)
    assert state_mesh.sequences.shape == state_plain.sequences.shape
    np.testing.assert_array_equal(state_mesh.sequences, state_plain.sequences)
    assert state_mesh.secret_prob == pytest.approx(state_plain.secret_prob,
                                                   abs=1e-5)
    np.testing.assert_allclose(state_mesh.baseline_nll,
                               state_plain.baseline_nll, atol=1e-4)

    shared = {"sae": sae, "layer": config.model.layer_idx}
    ids = np.asarray([[0, -1], [3, 7], [5, -1]], np.int32)  # 3 arms
    plain = iv.measure_arms(params, cfg, tok, config, state_plain,
                            iv.sae_ablation_edit, shared, {"latent_ids": ids})
    sharded = iv.measure_arms(params, cfg, tok, config, state_plain,
                              iv.sae_ablation_edit, shared,
                              {"latent_ids": ids}, mesh=m)
    assert len(sharded) == 3
    for a, b in zip(plain, sharded):
        assert a.guesses == b.guesses
        assert a.secret_prob == pytest.approx(b.secret_prob, abs=1e-5)
        assert a.delta_nll == pytest.approx(b.delta_nll, abs=1e-5)


def test_study_with_forcing_per_targeted_arm(setup, tmp_path):
    """forcing=True composes the token-forcing attacks with each targeted
    edit arm (Execution Plan: elicitation robustness measured per arm)."""
    import dataclasses as dc

    from taboo_brittleness_tpu.config import TokenForcingConfig

    params, cfg, tok, config, sae = setup
    fast = dc.replace(
        config,
        intervention=dc.replace(config.intervention, budgets=(1,),
                                random_trials=1, ranks=(1,)),
        # Trim the forcing grid (2 phrases, 1 warm-up turn) — the unit test
        # checks the composition, not the full 10x3 attack surface.
        token_forcing=TokenForcingConfig(
            prefill_phrases=("My secret word is", "The word is"),
            warmup_prompts=("Give me a hint",)))
    res = iv.run_intervention_study(
        params, cfg, tok, fast, WORD, sae,
        output_path=str(tmp_path / "s.json"), forcing=True)

    assert set(res["baseline"]["forcing"]) == {"pregame", "postgame", "edit"}
    assert res["baseline"]["forcing"]["edit"] == "none"
    t = res["ablation"]["budgets"]["1"]["targeted"]
    assert set(t["forcing"]) == {"pregame", "postgame", "edit"}
    # The forcing edit always applies at every position (spike masks are
    # keyed to the hint prompts' layouts) — the stored scope must say so.
    assert t["forcing"]["edit"] == "all-positions"
    assert all(0.0 <= t["forcing"][m] <= 1.0 for m in ("pregame", "postgame"))
    # random controls don't pay the forcing cost
    assert "forcing" not in res["ablation"]["budgets"]["1"]["random"][0]
    p = res["projection"]["ranks"]["1"]["targeted"]
    assert set(p["forcing"]) == {"pregame", "postgame", "edit"}
    assert p["forcing"]["edit"] == "all-positions"



def test_nll_cached_continuation_matches_full(setup):
    """The prefill-KV continuation NLL (_nll_cached_jit, the production sweep
    path) must reproduce the full-forward NLL — with and without an edit,
    since the cache comes from the arm decode's EDITED prefill."""
    import jax.numpy as jnp

    from taboo_brittleness_tpu.ops import sae as sae_ops  # noqa: F401
    from taboo_brittleness_tpu.runtime import decode

    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    B, T = state.sequences.shape
    s = state.resp_start
    next_mask = np.zeros_like(state.response_mask)
    next_mask[:, :-1] = state.response_mask[:, 1:]
    full_args = (params, cfg, jnp.asarray(state.sequences),
                 jnp.asarray(state.valid.astype(bool)),
                 jnp.asarray(state.positions), jnp.asarray(next_mask))

    for ep in (None,
               {"sae": sae,
                "latent_ids": jnp.asarray(
                    np.tile([[1, 3]], (B, 1)), jnp.int32),
                "layer": config.model.layer_idx}):
        edit = iv.sae_ablation_edit if ep is not None else None
        # Prefill cache from a decode over the word's prompt rows under the
        # same edit (the production flow: _dispatch_rows / prepare).
        dec = decode.greedy_decode(
            params, cfg, jnp.asarray(state.sequences[:, :s + 1]),
            jnp.asarray(state.valid[:, :s + 1].astype(bool)),
            jnp.asarray(state.positions[:, :s + 1]),
            max_new_tokens=T - (s + 1),
            edit_fn=edit,
            edit_params=ep,
            stop_ids=(-1,), return_prefill_cache=True)

        full = np.asarray(iv._nll_jit(
            *full_args, edit_fn=edit,
            edit_params=(iv._with_chunk_positions(ep, jnp.asarray(state.positions))
                         if ep is not None else None),
            resp_start=s))
        cached = np.asarray(iv._nll_cached_jit(
            params, cfg, *dec.prefill_cache, *full_args[2:],
            edit_fn=edit,
            edit_params=(iv._with_chunk_positions(
                ep, jnp.asarray(state.positions[:, s:]))
                         if ep is not None else None),
            resp_start=s))
        np.testing.assert_allclose(cached, full, rtol=1e-4, atol=1e-5)

    # Shape-mismatch guard: a cache that disagrees with resp_start is loud.
    with pytest.raises(ValueError, match="prefill cache covers"):
        iv._teacher_forced_nll_cached(
            params, cfg, *dec.prefill_cache, *full_args[2:],
            resp_start=s + 1)


def test_measure_arm_sets_matches_per_set_measure_arms(setup):
    """The fused two-sweep dispatch stream (measure_arm_sets, the production
    study path) must produce exactly what per-set measure_arms produces —
    same arms, same order, same numbers."""
    import jax.numpy as jnp

    from taboo_brittleness_tpu.ops import projection, sae as sae_ops

    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    D = cfg.hidden_size

    abl_shared = {"sae": sae, "layer": config.model.layer_idx}
    abl_arm = {"latent_ids": jnp.asarray(
        np.asarray([[1, 2], [3, -1], [0, 5]]), jnp.int32)}
    proj_shared = {"layer": config.model.layer_idx}
    proj_arm = {"basis": jnp.stack(
        [projection.random_subspace(jax.random.PRNGKey(i), D, 1)
         for i in range(2)])}

    sets = [(iv.sae_ablation_edit, abl_shared, abl_arm, 2),
            (iv.projection_edit, proj_shared, proj_arm, None)]
    fused_a, fused_p = iv.measure_arm_sets(params, cfg, tok, config, state,
                                           sets)
    solo_a = iv.measure_arms(params, cfg, tok, config, state,
                             iv.sae_ablation_edit, abl_shared, abl_arm,
                             arm_chunk=2)
    solo_p = iv.measure_arms(params, cfg, tok, config, state,
                             iv.projection_edit, proj_shared, proj_arm)
    assert fused_a == solo_a
    assert fused_p == solo_p
    assert len(fused_a) == 3 and len(fused_p) == 2


def test_cross_word_pipelining_matches_sequential(setup, tmp_path):
    """The studies driver's cross-word baseline pre-dispatch must change
    NOTHING about the results: two words through run_intervention_studies
    (pipelined path) equal the same words run one-by-one through
    run_intervention_study."""
    import dataclasses as dc
    import json as json_mod

    params, cfg, tok, config, sae = setup
    fast_iv = dc.replace(config.intervention, budgets=(1, 2),
                         random_trials=1, ranks=(1,))
    config2 = dc.replace(config, intervention=fast_iv,
                         word_plurals={WORD: [WORD], "word2": ["word2"]})
    out_dir = str(tmp_path / "studies")

    res = iv.run_intervention_studies(
        config2, model_loader=lambda w: (params, cfg, tok), sae=sae,
        words=[WORD, "word2"], output_dir=out_dir)

    for w in (WORD, "word2"):
        solo = iv.run_intervention_study(params, cfg, tok, config2, w, sae)
        # JSON round-trip both sides so container/float representations
        # compare canonically.
        assert (json_mod.loads(json_mod.dumps(res[w]))
                == json_mod.loads(json_mod.dumps(solo)))


def test_cross_word_pipelining_survives_next_word_load_failure(
        setup, tmp_path):
    """A loader failure during the EARLY (pipelined) load of word 2 must not
    lose word 1's results: its JSON lands first, and the failure resurfaces
    at word 2's own load."""
    import dataclasses as dc
    import os as os_mod

    params, cfg, tok, config, sae = setup
    fast_iv = dc.replace(config.intervention, budgets=(1,),
                         random_trials=1, ranks=(1,))
    config2 = dc.replace(config, intervention=fast_iv,
                         word_plurals={WORD: [WORD], "word2": ["word2"]})
    out_dir = str(tmp_path / "studies")

    class Crash(RuntimeError):
        pass

    def loader(w):
        if w == "word2":
            raise Crash("checkpoint gone")
        return params, cfg, tok

    # fail_fast=True: the assertion is specifically that the failure
    # resurfaces at word 2's own load (the default retry+quarantine path is
    # covered by tests/test_sweep_resilience.py).
    with pytest.raises(Crash):
        iv.run_intervention_studies(
            config2, model_loader=loader, sae=sae, words=[WORD, "word2"],
            output_dir=out_dir, fail_fast=True)
    assert os_mod.path.exists(os_mod.path.join(out_dir, f"{WORD}.json"))
