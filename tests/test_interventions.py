"""Intervention sweep mechanics on the tiny model: edits bite, controls don't,
measurements are well-formed (Execution Plan items (e)/(f))."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.config import (
    Config, ExperimentConfig, InterventionConfig, ModelConfig)
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.ops import sae as sae_ops
from taboo_brittleness_tpu.pipelines import interventions as iv
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(11), cfg)
    tok = WordTokenizer([WORD, "hint", "clue", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=5),
        intervention=InterventionConfig(
            budgets=(1, 2), random_trials=2, ranks=(1, 2), spike_top_k=2),
        word_plurals={WORD: [WORD, WORD + "s"]},
        prompts=["Give me a hint", "a clue"],
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), d_model=cfg.hidden_size,
                              d_sae=32)
    return params, cfg, tok, config, sae


def test_prepare_word_state_shapes(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    B = len(config.prompts)
    assert state.sequences.shape[0] == B
    assert state.residual.shape == (*state.sequences.shape, cfg.hidden_size)
    assert state.spike_pos.shape == (B, config.intervention.spike_top_k)
    assert 0.0 <= state.secret_prob <= 1.0
    # spikes are inside the response region
    for b in range(B):
        for p in state.spike_pos[b]:
            assert state.response_mask[b, p]
    # baseline NLL nonzero only where next token is response
    assert (state.baseline_nll >= 0).all()
    assert len(state.guesses) == B


def test_zero_latent_ablation_is_noop_arm(setup):
    """m=0 (all -1 ids) must leave generation and NLL unchanged — the identity
    control that validates the delta-patching edit."""
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    ep = {"sae": sae, "latent_ids": jnp.asarray([-1], jnp.int32),
          "layer": config.model.layer_idx}
    arm = iv.measure_arm(params, cfg, tok, config, state, iv.sae_ablation_edit, ep)
    assert arm.delta_nll == pytest.approx(0.0, abs=1e-4)
    assert arm.secret_prob == pytest.approx(state.secret_prob, abs=1e-5)
    assert arm.guesses == state.guesses


def test_ablation_sweep_structure(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    res = iv.run_ablation_sweep(params, cfg, tok, config, state, sae)
    assert set(res["budgets"]) == {"1", "2"}
    for m, block in res["budgets"].items():
        assert set(block) == {"targeted", "random_mean", "random"}
        assert len(block["random"]) == config.intervention.random_trials
        for key in ("secret_prob", "delta_nll", "leak_rate", "prompt_accuracy"):
            assert key in block["targeted"]
            assert key in block["random_mean"]


def test_projection_edit_changes_model_and_sweep_runs(setup):
    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    res = iv.run_projection_sweep(params, cfg, tok, config, state)
    assert set(res["ranks"]) == {"1", "2"}
    # removing a rank-2 subspace of the actual residual stream must perturb NLL
    r2 = res["ranks"]["2"]["targeted"]
    assert abs(r2["delta_nll"]) > 0.0


def test_spike_masked_arm_differs_from_full_arm(setup):
    """config.intervention.spike_masked edits ONLY the baseline spike
    positions — a different experiment from the every-position edit (VERDICT
    round-1 item 7), so the two arms must measurably differ."""
    import dataclasses

    params, cfg, tok, config, sae = setup
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)

    # A strong projection edit makes the difference visible on a tiny model.
    basis, _ = __import__("taboo_brittleness_tpu.ops.projection",
                          fromlist=["principal_subspace"]).principal_subspace(
        jnp.asarray(state.residual.reshape(-1, cfg.hidden_size)), rank=2)

    ep_full = {"basis": basis, "layer": config.model.layer_idx}
    full = iv.measure_arm(params, cfg, tok, config, state,
                          iv.projection_edit, ep_full)

    masked_cfg = dataclasses.replace(
        config, intervention=dataclasses.replace(
            config.intervention, spike_masked=True))
    extra = iv._spike_mask_extra(masked_cfg, state)
    assert "spike_positions" in extra
    ep_masked = {**ep_full, **extra}
    masked = iv.measure_arm(params, cfg, tok, config, state,
                            iv.projection_edit, ep_masked)

    # Full-position editing perturbs the continuation NLL strictly more than
    # spike-only editing; the two arms must not coincide.
    assert abs(masked.delta_nll) < abs(full.delta_nll)
    assert masked.delta_nll != pytest.approx(full.delta_nll, abs=1e-6)


def test_spike_masked_sweep_runs_and_differs(setup):
    import dataclasses

    params, cfg, tok, config, sae = setup
    masked_cfg = dataclasses.replace(
        config, intervention=dataclasses.replace(
            config.intervention, budgets=(2,), random_trials=1,
            spike_masked=True))
    state = iv.prepare_word_state(params, cfg, tok, config, WORD)
    res_masked = iv.run_ablation_sweep(params, cfg, tok, masked_cfg, state, sae)
    res_full = iv.run_ablation_sweep(
        params, cfg, tok, dataclasses.replace(
            config, intervention=dataclasses.replace(
                config.intervention, budgets=(2,), random_trials=1)),
        state, sae)
    t_m = res_masked["budgets"]["2"]["targeted"]
    t_f = res_full["budgets"]["2"]["targeted"]
    # Same targeted latents, different edit footprint.
    assert t_m["delta_nll"] != pytest.approx(t_f["delta_nll"], abs=1e-9)


def test_full_study_writes_json(setup, tmp_path):
    params, cfg, tok, config, sae = setup
    out = str(tmp_path / "study.json")
    res = iv.run_intervention_study(
        params, cfg, tok, config, WORD, sae, output_path=out)
    assert set(res) == {"word", "baseline", "ablation", "projection"}
    import json
    with open(out) as f:
        loaded = json.load(f)
    assert loaded["word"] == WORD
