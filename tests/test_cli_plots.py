"""CLI arg-parsing and plotting smoke tests."""

import json
import os

import numpy as np
import pytest

from taboo_brittleness_tpu import cli, plots


def test_cli_parser_covers_all_subcommands():
    p = cli.build_parser()
    for argv in (
        ["generate", "--parity-dump"],
        ["logit-lens", "--words", "ship"],
        ["sae-baseline", "--sae-npz", "x.npz"],
        ["interventions", "--word", "ship", "--sae-npz", "x.npz"],
        ["token-forcing", "--modes", "pregame"],
        ["prompting", "--modes", "naive"],
    ):
        args = p.parse_args(argv)
        assert callable(args.fn)


def test_cli_sae_requires_npz():
    p = cli.build_parser()
    args = p.parse_args(["sae-baseline"])
    args.sae_npz = None
    with pytest.raises(SystemExit):
        cli._sae(cli.Config(), None)


def test_plot_token_probability_full_and_compact(tmp_path):
    rng = np.random.default_rng(0)
    L, T, V = 6, 5, 11
    all_probs = rng.random((L, T, V)).astype(np.float32)
    words = [f"t{i}" for i in range(T)]

    fig = plots.plot_token_probability(all_probs, token_id=3, input_words=words,
                                       start_idx=1, figsize=(4, 3),
                                       font_size=8, title_font_size=9,
                                       tick_font_size=8)
    path = str(tmp_path / "full.png")
    plots.save_fig(fig, path, dpi=50)
    assert os.path.getsize(path) > 0

    compact = all_probs[:, :, 3]
    fig2 = plots.plot_token_probability(compact, input_words=words,
                                        figsize=(4, 3), font_size=8,
                                        title_font_size=9, tick_font_size=8)
    plots.save_fig(fig2, str(tmp_path / "compact.png"), dpi=50)

    with pytest.raises(ValueError):
        plots.plot_token_probability(all_probs)  # 3-D needs token_id


def test_plot_brittleness_curves(tmp_path):
    arm = lambda v: {"secret_prob_drop": v, "delta_nll": v / 2}
    sweep = {
        "word": "ship",
        "budgets": {
            "1": {"targeted": arm(0.1), "random_mean": arm(0.01),
                  "random": [arm(0.01), arm(0.02)]},
            "4": {"targeted": arm(0.4), "random_mean": arm(0.05),
                  "random": [arm(0.04), arm(0.06)]},
        },
    }
    fig = plots.plot_brittleness_curves(sweep, figsize=(4, 3))
    plots.save_fig(fig, str(tmp_path / "curves.png"), dpi=50)
    assert os.path.getsize(str(tmp_path / "curves.png")) > 0


def test_cli_interventions_sweep_mode(tmp_path, monkeypatch):
    """`interventions` without --word runs the resumable multi-word driver
    end-to-end (tiny model, stub loader) and writes one JSON per word."""
    import dataclasses

    import jax

    from taboo_brittleness_tpu.config import (
        Config, ExperimentConfig, InterventionConfig, ModelConfig)
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(2), cfg)
    tok = WordTokenizer(["moon", "hint", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=4),
        intervention=InterventionConfig(budgets=(1,), random_trials=1,
                                        ranks=(1,), spike_top_k=2),
        word_plurals={"moon": ["moon"]},  # config.words derives from the keys
        prompts=["Give me a hint"],
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), cfg.hidden_size, 16)
    sae_path = str(tmp_path / "sae.npz")
    np.savez(sae_path, W_enc=np.asarray(sae.w_enc), b_enc=np.asarray(sae.b_enc),
             W_dec=np.asarray(sae.w_dec), b_dec=np.asarray(sae.b_dec),
             threshold=np.asarray(sae.threshold))

    monkeypatch.setattr(cli, "_load", lambda args: config)
    monkeypatch.setattr(cli, "_mesh", lambda c: None)
    monkeypatch.setattr(cli, "_loader",
                        lambda c, a, mesh=None: (lambda w: (params, cfg, tok)))
    monkeypatch.chdir(tmp_path)

    p = cli.build_parser()
    args = p.parse_args(["interventions", "--sae-npz", sae_path])
    assert args.fn(args) == 0
    out = tmp_path / "results" / "interventions" / "moon.json"
    assert out.exists()
    with open(out) as f:
        study = json.load(f)
    assert set(study) == {"word", "baseline", "ablation", "projection"}
    # Brittleness curves saved next to the JSON (L6 parity for this pipeline).
    for key in ("ablation", "projection"):
        assert (out.parent / "plots" / f"moon_{key}.png").exists()

    # Second run resumes from the existing JSON (no error, same file).
    assert args.fn(args) == 0
