"""Lens-draft speculative decoding (runtime/speculate.py, ISSUE 9).

The contract under test, in order of importance:

1. **Exact greedy equivalence** — the speculative decoder's token streams
   (tokens, lengths, sequences, sequence_valid) are IDENTICAL
   (``np.array_equal``, not allclose) to vanilla ``greedy_decode`` across
   every intervention scenario (none / SAE ablation / spike-masked /
   projection / forcing prefills), early-stop rows, ragged padded batches,
   and a degenerate (uselessly shallow) draft.  This is lossless BY
   CONSTRUCTION: every emitted token is the full model's verify-pass argmax;
   the draft only chooses which positions verify together.
2. **Measurement-path contract** — the decode-captured residual is bitwise
   equal at the small chunk shapes tier-1 pins, and f32-rounding-close in
   general (speculation changes forward SHAPES, and XLA's shape-dependent
   fusion rounds last bits differently — the PR-8 hazard class, here
   measured ~1e-7 relative; see ``speculate.capture_extension_enabled``).
   Hence the gating: ``TBX_SPECULATE=1`` covers non-capture decodes and
   keeps every study JSON byte-identical; ``TBX_SPECULATE_CAPTURE=1``
   extends to capture launches with exact tokens and allclose floats.
3. **Calibration** — the host-side (k, G) chooser over the committed tiny
   lens-agreement fixture, and the env → artifact → default plan resolution.
4. **AOT coverage** — ``study_program_specs`` mirrors the speculative
   launch signatures exactly (zero registry misses, like the fused gate).
5. **Fault/drain** — a poisoned ``speculate.verify`` launch rides the
   retry→quarantine path; a drain mid-decode still finishes the word
   exactly (drain stays word-granular).
6. **Bench** — the ``spec_ab`` stage and its regression-gated
   ``spec_ab.spec_speedup`` / ``spec_ab.accept_rate`` metrics.
"""

import io
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.config import (
    Config, ExperimentConfig, InterventionConfig, ModelConfig)
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.models.gemma2 import KVCache, forward
from taboo_brittleness_tpu.ops import sae as sae_ops
from taboo_brittleness_tpu.perf import spec_calibrate
from taboo_brittleness_tpu.pipelines import interventions as iv
from taboo_brittleness_tpu.runtime import (
    aot, chat, decode, resilience, speculate, supervise)
from taboo_brittleness_tpu.runtime.resilience import FaultInjector, InjectedFault
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
for _p in (REPO, TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

import bench_compare  # noqa: E402

FIXTURE_PROCESSED = os.path.join(REPO, "tests", "fixtures", "speculate",
                                 "processed")
WORD = "moon"


@pytest.fixture(scope="module")
def setup():
    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(11), cfg)
    tok = WordTokenizer([WORD, "hint", "clue", "Give", "me", "a"],
                        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=5),
        intervention=InterventionConfig(
            budgets=(1, 2), random_trials=1, ranks=(1,), spike_top_k=2,
            arm_chunk=2),
        word_plurals={WORD: [WORD, WORD + "s"]},
        prompts=["Give me a hint", "a clue"],
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), d_model=cfg.hidden_size,
                              d_sae=32)
    return params, cfg, tok, config, sae


@pytest.fixture()
def fresh_registry():
    aot.reset()
    yield
    aot.reset()


@pytest.fixture()
def clean_injector():
    resilience.set_injector(FaultInjector())
    yield resilience.get_injector()
    resilience.set_injector(FaultInjector())


def _prompt_args(cfg, rows=4, seed=5, lo=3, hi=8):
    rng = np.random.default_rng(seed)
    prompts = [list(rng.integers(1, cfg.vocab_size,
                                 size=int(rng.integers(lo, hi))))
               for _ in range(rows)]
    padded, valid, positions = decode.pad_prompts(prompts)
    return (jnp.asarray(padded), jnp.asarray(valid), jnp.asarray(positions))


def _scenario(name, cfg, sae, rows, seed=17):
    rng = np.random.default_rng(seed)
    if name == "none":
        return None, None
    if name == "sae":
        return iv.sae_ablation_edit, {
            "sae": sae, "layer": 2,
            "latent_ids": jnp.asarray(
                rng.integers(0, sae.w_enc.shape[1], size=(rows, 3)),
                jnp.int32)}
    if name == "sae_spike_masked":
        return iv.sae_ablation_edit, {
            "sae": sae, "layer": 2,
            "latent_ids": jnp.asarray(
                rng.integers(0, sae.w_enc.shape[1], size=(rows, 3)),
                jnp.int32),
            "spike_positions": jnp.asarray(
                rng.integers(0, 6, size=(rows, 2)), jnp.int32)}
    if name == "projection":
        basis, _ = np.linalg.qr(rng.standard_normal((cfg.hidden_size, 2)))
        return iv.projection_edit, {
            "layer": 2,
            "basis": jnp.tile(jnp.asarray(basis, jnp.float32)[None],
                              (rows, 1, 1))}
    raise AssertionError(name)


def _assert_stream_equal(van, res):
    np.testing.assert_array_equal(np.asarray(van.tokens),
                                  np.asarray(res.tokens))
    np.testing.assert_array_equal(np.asarray(van.lengths),
                                  np.asarray(res.lengths))
    np.testing.assert_array_equal(np.asarray(van.sequences),
                                  np.asarray(res.sequences))
    np.testing.assert_array_equal(np.asarray(van.sequence_valid),
                                  np.asarray(res.sequence_valid))


# ---------------------------------------------------------------------------
# Gate + routing.
# ---------------------------------------------------------------------------

def test_speculate_off_by_default(monkeypatch):
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    assert speculate.enabled() is False
    assert speculate.should_speculate(capture=False) is False


def test_speculate_never_engages_under_a_mesh(monkeypatch):
    monkeypatch.setenv("TBX_SPECULATE", "1")
    assert speculate.should_speculate(capture=False) is True
    assert speculate.should_speculate(capture=False, mesh_sharded=True) is False


def test_capture_launches_need_the_extension(monkeypatch):
    monkeypatch.setenv("TBX_SPECULATE", "1")
    monkeypatch.delenv("TBX_SPECULATE_CAPTURE", raising=False)
    assert speculate.should_speculate(capture=True) is False
    monkeypatch.setenv("TBX_SPECULATE_CAPTURE", "1")
    assert speculate.should_speculate(capture=True) is True


# ---------------------------------------------------------------------------
# Exact greedy equivalence, per scenario.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("scenario", ["none", "sae", "sae_spike_masked",
                                      "projection"])
def test_exact_greedy_equivalence_per_scenario(setup, scenario):
    """Token streams are bit-identical to vanilla greedy under every
    intervention scenario; the captured residual is bit-identical at these
    chunk shapes too, except under the projection edit whose batched
    subspace matmul rounds last bits differently per chunk width (tokens
    stay exact — documented in the module docstring)."""
    params, cfg, tok, config, sae = setup
    rows, N = 4, 6
    args = _prompt_args(cfg, rows=rows)
    edit_fn, ep = _scenario(scenario, cfg, sae, rows)
    van = decode.greedy_decode(
        params, cfg, *args, max_new_tokens=N, stop_ids=(-1,),
        edit_fn=edit_fn, edit_params=ep, capture_residual_layer=2,
        return_prefill_cache=True)
    res, stats = speculate.speculative_decode(
        params, cfg, *args, max_new_tokens=N, draft_layer=2, block_size=3,
        stop_ids=(-1,), edit_fn=edit_fn, edit_params=ep,
        capture_residual_layer=2, return_prefill_cache=True)
    _assert_stream_equal(van, res)
    assert stats.blocks >= 1 and stats.emitted + rows == int(
        np.asarray(res.lengths).sum())
    sv = np.asarray(van.sequence_valid)
    a, b = np.asarray(van.residual)[sv], np.asarray(res.residual)[sv]
    if scenario == "projection":
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    else:
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(np.asarray(van.prefill_cache[0]),
                                  np.asarray(res.prefill_cache[0]))
    np.testing.assert_array_equal(np.asarray(van.prefill_cache[2]),
                                  np.asarray(res.prefill_cache[2]))


def test_exact_with_early_stop_rows(setup):
    """Rows that emit a real stop id mid-stream stop exactly where vanilla
    stops (stop token kept, pad after), while other rows run the budget."""
    params, cfg, tok, config, sae = setup
    rows, N = 4, 6
    args = _prompt_args(cfg, rows=rows, seed=9)
    probe = decode.greedy_decode(params, cfg, *args, max_new_tokens=N,
                                 stop_ids=(-1,))
    stop_ids = (int(np.asarray(probe.tokens)[0, 1]),)
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=N,
                               stop_ids=stop_ids, capture_residual_layer=2)
    assert np.asarray(van.lengths).min() < N, "no row stopped early"
    res, _ = speculate.speculative_decode(
        params, cfg, *args, max_new_tokens=N, draft_layer=2, block_size=3,
        stop_ids=stop_ids, capture_residual_layer=2)
    _assert_stream_equal(van, res)
    sv = np.asarray(van.sequence_valid)
    np.testing.assert_array_equal(np.asarray(van.residual)[sv],
                                  np.asarray(res.residual)[sv])


def test_exact_when_first_token_is_stop(setup):
    """A row whose FIRST token is a stop id emits exactly one token (the
    stop, kept — greedy_decode's recording semantics) and never enters a
    verify block."""
    params, cfg, tok, config, sae = setup
    rows, N = 3, 5
    args = _prompt_args(cfg, rows=rows, seed=13)
    probe = decode.greedy_decode(params, cfg, *args, max_new_tokens=N,
                                 stop_ids=(-1,))
    stop_ids = (int(np.asarray(probe.tokens)[1, 0]),)
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=N,
                               stop_ids=stop_ids)
    assert np.asarray(van.lengths).min() == 1
    res, _ = speculate.speculative_decode(
        params, cfg, *args, max_new_tokens=N, draft_layer=1, block_size=2,
        stop_ids=stop_ids)
    _assert_stream_equal(van, res)


@pytest.mark.parametrize("block_size", [1, 2, 5])
def test_exact_across_block_sizes(setup, block_size):
    params, cfg, tok, config, sae = setup
    args = _prompt_args(cfg, rows=4, seed=23)
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=5,
                               stop_ids=(-1,))
    res, _ = speculate.speculative_decode(
        params, cfg, *args, max_new_tokens=5, draft_layer=2,
        block_size=block_size, stop_ids=(-1,))
    _assert_stream_equal(van, res)


def test_degenerate_shallow_draft_still_exact(setup):
    """k=0 drafts from the first layer's lens — rejections abound, but the
    output stream is still exactly the vanilla stream (the draft never
    touches an emitted token) and every block still advances ≥ 1 token per
    active row."""
    params, cfg, tok, config, sae = setup
    args = _prompt_args(cfg, rows=4, seed=31)
    N = 6
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=N,
                               stop_ids=(-1,))
    res, stats = speculate.speculative_decode(
        params, cfg, *args, max_new_tokens=N, draft_layer=0, block_size=4,
        stop_ids=(-1,))
    _assert_stream_equal(van, res)
    assert stats.accepted < stats.drafted          # real rejections happened
    assert stats.accept_rate < 1.0
    assert stats.blocks <= N                       # ≥1 token/block guarantee


def test_exact_through_generate_with_ragged_padded_batches(setup, monkeypatch,
                                                           fresh_registry):
    """decode.generate end-to-end: ragged prompt lengths + pad_to_multiple
    bucketing, vanilla vs TBX_SPECULATE=1 — identical tokens AND texts."""
    params, cfg, tok, config, sae = setup
    prompts = ["Give me a hint", "a", "Give me a hint Give me a hint",
               "clue me"]
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    van, van_texts, _ = decode.generate(params, cfg, tok, prompts,
                                        max_new_tokens=6, pad_to_multiple=8)
    monkeypatch.setenv("TBX_SPECULATE", "1")
    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "2")
    monkeypatch.setenv("TBX_SPEC_BLOCK", "3")
    res, res_texts, _ = decode.generate(params, cfg, tok, prompts,
                                        max_new_tokens=6, pad_to_multiple=8)
    _assert_stream_equal(van, res)
    assert van_texts == res_texts
    s = aot.stats()
    assert s.get("speculate.verify", {}).get("programs", 0) >= 0  # routed
    assert "speculate.prefill" in s                               # engaged


def test_exact_with_forcing_prefills(setup, monkeypatch, fresh_registry):
    """The token-forcing scenario: prefilled model turns through generate,
    vanilla vs speculative — identical streams (forcing success metrics are
    pure string scores over these)."""
    params, cfg, tok, config, sae = setup
    prompts = ["", "", ""]
    prefills = ["Give me", "a clue", "hint hint"]
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    van, vt, _ = decode.generate(params, cfg, tok, prompts,
                                 prefills=prefills, max_new_tokens=5)
    monkeypatch.setenv("TBX_SPECULATE", "1")
    res, rt, _ = decode.generate(params, cfg, tok, prompts,
                                 prefills=prefills, max_new_tokens=5)
    _assert_stream_equal(van, res)
    assert vt == rt


def test_forcing_pipeline_decode_rendered_speculates(setup, monkeypatch,
                                                     fresh_registry):
    """token_forcing._decode_rendered routes through the speculative decoder
    under TBX_SPECULATE=1 and returns identical texts."""
    from taboo_brittleness_tpu.pipelines import token_forcing

    params, cfg, tok, config, sae = setup
    rendered = [chat.render_chat([chat.Turn("user", "")], prefill=p)
                for p in ("Give me", "a clue")]
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    van = token_forcing._decode_rendered(params, cfg, tok, rendered,
                                         max_new_tokens=5)
    monkeypatch.setenv("TBX_SPECULATE", "1")
    res = token_forcing._decode_rendered(params, cfg, tok, rendered,
                                         max_new_tokens=5)
    assert van == res
    assert "speculate.verify" in aot.stats()


# ---------------------------------------------------------------------------
# Study integration: JSON identity + capture-extension contract.
# ---------------------------------------------------------------------------

def test_study_json_byte_identical_under_speculation(setup, monkeypatch,
                                                     fresh_registry):
    """The whole-word study (baseline + both sweeps + forcing attacks) is
    BYTE-identical under TBX_SPECULATE=1: capture launches stay vanilla by
    default, and the forcing decodes — which do speculate — are pure token
    paths.  The speculative path must actually have engaged (counted
    launches), or this test proves nothing."""
    from taboo_brittleness_tpu.obs import metrics as obs_metrics

    params, cfg, tok, config, sae = setup
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    vanilla = iv.run_intervention_study(params, cfg, tok, config, WORD, sae,
                                        forcing=True)
    monkeypatch.setenv("TBX_SPECULATE", "1")
    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "2")
    monkeypatch.setenv("TBX_SPEC_BLOCK", "2")
    before = obs_metrics.counter("speculate.launches").value
    spec = iv.run_intervention_study(params, cfg, tok, config, WORD, sae,
                                     forcing=True)
    assert obs_metrics.counter("speculate.launches").value > before
    assert (json.dumps(vanilla, sort_keys=True, default=float)
            == json.dumps(spec, sort_keys=True, default=float))


def _compare_json(a, b, path=""):
    """Structural study-JSON comparison: discrete fields (strings, ints,
    bools) must match EXACTLY; floats to f32-rounding tolerance."""
    assert type(a) is type(b), f"{path}: {type(a)} vs {type(b)}"
    if isinstance(a, dict):
        assert set(a) == set(b), f"{path}: keys differ"
        for k in a:
            _compare_json(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), f"{path}: length differs"
        for i, (x, y) in enumerate(zip(a, b)):
            _compare_json(x, y, f"{path}[{i}]")
    elif isinstance(a, float):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5,
                                   err_msg=path)
    else:
        assert a == b, f"{path}: {a!r} != {b!r}"


def test_study_capture_extension_exact_tokens_close_floats(setup, monkeypatch,
                                                           fresh_registry):
    """TBX_SPECULATE_CAPTURE=1 puts the study's capture decodes on the
    speculative path too: every DISCRETE science field (response texts,
    guesses, leak/accuracy) is byte-identical, continuous readouts agree to
    f32 rounding (the shape-dependent-fusion bound the module docstring
    documents)."""
    params, cfg, tok, config, sae = setup
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    vanilla = iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    monkeypatch.setenv("TBX_SPECULATE", "1")
    monkeypatch.setenv("TBX_SPECULATE_CAPTURE", "1")
    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "2")
    monkeypatch.setenv("TBX_SPEC_BLOCK", "2")
    spec = iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    assert "speculate.verify" in aot.stats()
    assert (vanilla["baseline"]["response_texts"]
            == spec["baseline"]["response_texts"])
    assert vanilla["baseline"]["guesses"] == spec["baseline"]["guesses"]
    _compare_json(vanilla, spec)


def test_warm_start_then_capture_study_zero_misses(setup, monkeypatch,
                                                   fresh_registry):
    """Mirror of the fused zero-miss gate: study_program_specs' speculative
    mirror (prefill/draft/verify/flush per distinct calibrated plan) must
    match the real launch signatures exactly — a drifting signature fails
    here, not silently on a TPU round."""
    params, cfg, tok, config, sae = setup
    monkeypatch.setenv("TBX_SPECULATE", "1")
    monkeypatch.setenv("TBX_SPECULATE_CAPTURE", "1")
    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "2")
    monkeypatch.setenv("TBX_SPEC_BLOCK", "2")
    rep = iv.warm_start_study(params, cfg, tok, config, sae, store=None)
    assert rep["errors"] == 0
    spec_labels = [r["label"] for r in rep["programs"]
                   if r["label"].startswith("spec.")]
    # 4 programs x 3 trios (baseline/ablation/projection) x 1 plan.
    assert len(spec_labels) == 12, spec_labels
    iv.run_intervention_study(params, cfg, tok, config, WORD, sae)
    s = aot.stats()
    for entry in ("speculate.prefill", "speculate.draft",
                  "speculate.verify", "speculate.flush"):
        assert s[entry]["misses"] == 0, (entry, s)
        assert s[entry]["fallbacks"] == 0, (entry, s)
        assert s[entry]["hits"] > 0, (entry, s)
    assert s.get("decode", {}).get("hits", 0) == 0, s


# ---------------------------------------------------------------------------
# Plan resolution + calibrator (committed tiny lens-agreement fixture).
# ---------------------------------------------------------------------------

def test_resolve_plan_env_beats_artifact_beats_default(setup, monkeypatch,
                                                       tmp_path):
    params, cfg, tok, config, sae = setup
    monkeypatch.delenv("TBX_SPEC_DRAFT_LAYER", raising=False)
    monkeypatch.delenv("TBX_SPEC_BLOCK", raising=False)
    monkeypatch.delenv("TBX_SPEC_CALIBRATION", raising=False)
    speculate.set_active_word(None)
    plan = speculate.resolve_plan(cfg)
    assert plan.source == "default"
    assert plan.draft_layer == speculate.default_draft_layer(cfg)
    assert plan.block_size == speculate.DEFAULT_BLOCK

    art = tmp_path / "cal.json"
    art.write_text(json.dumps({
        "words": {"moon": {"draft_layer": 1, "block_size": 4}},
        "default": {"draft_layer": 2, "block_size": 2}}))
    monkeypatch.setenv("TBX_SPEC_CALIBRATION", str(art))
    speculate.set_active_word("moon")
    plan = speculate.resolve_plan(cfg)
    assert (plan.draft_layer, plan.block_size,
            plan.source) == (1, 4, "calibration")
    speculate.set_active_word("ghost")          # uncalibrated → default block
    plan = speculate.resolve_plan(cfg)
    assert (plan.draft_layer, plan.block_size) == (2, 2)

    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "0")
    monkeypatch.setenv("TBX_SPEC_BLOCK", "5")
    plan = speculate.resolve_plan(cfg)
    assert (plan.draft_layer, plan.block_size, plan.source) == (0, 5, "env")
    speculate.set_active_word(None)


def test_resolve_plan_clamps_to_architecture(setup, monkeypatch):
    params, cfg, tok, config, sae = setup
    monkeypatch.setenv("TBX_SPEC_DRAFT_LAYER", "99")
    monkeypatch.setenv("TBX_SPEC_BLOCK", "0")
    plan = speculate.resolve_plan(cfg)
    assert plan.draft_layer == cfg.num_layers - 2
    assert plan.block_size == 1


def test_expected_tokens_formula():
    assert spec_calibrate.expected_tokens(0.0, 4) == 1.0      # bonus only
    assert spec_calibrate.expected_tokens(1.0, 4) == 5.0      # all accepted
    np.testing.assert_allclose(
        spec_calibrate.expected_tokens(0.5, 2), 1 + 0.5 + 0.25)


def test_layer_agreement_final_layer_is_one():
    arr = np.array([[1, 2, 3, 4], [5, 2, 7, 4], [5, 6, 7, 8]])
    agr = spec_calibrate.layer_agreement(arr)
    assert agr[-1] == 1.0
    np.testing.assert_allclose(agr, [0.0, 0.5, 1.0])


def test_calibrator_reads_committed_fixture(setup):
    """The committed tiny-model lens summaries drive a full calibration: a
    real [L] agreement vector (final layer ≡ 1.0), an admissible plan, and
    the artifact schema the dispatcher consumes."""
    params, cfg, tok, config, sae = setup
    agr = spec_calibrate.word_agreement(FIXTURE_PROCESSED, WORD)
    assert agr is not None and agr.shape == (cfg.num_layers,)
    assert agr[-1] == 1.0
    assert np.all((agr >= 0) & (agr <= 1))
    plan = spec_calibrate.calibrate_word(agr, cfg)
    assert 0 <= plan["draft_layer"] <= cfg.num_layers - 2
    assert plan["block_size"] >= 1
    assert {"agreement", "expected_tokens_per_verify",
            "expected_speedup"} <= set(plan)
    art = spec_calibrate.calibrate_words(FIXTURE_PROCESSED, [WORD, "ghost"],
                                         cfg)
    assert art["schema"] == spec_calibrate.SCHEMA_VERSION
    assert list(art["words"]) == [WORD]
    assert art["uncalibrated"] == ["ghost"]
    assert art["default"]["draft_layer"] == plan["draft_layer"]


def test_calibration_artifact_round_trip_through_dispatch(setup, monkeypatch,
                                                          tmp_path):
    """calibrate_words → write_calibration → resolve_plan: the full artifact
    path the production sweep takes."""
    params, cfg, tok, config, sae = setup
    art = spec_calibrate.calibrate_words(FIXTURE_PROCESSED, [WORD], cfg)
    path = tmp_path / "spec_calibration.json"
    spec_calibrate.write_calibration(str(path), art)
    monkeypatch.delenv("TBX_SPEC_DRAFT_LAYER", raising=False)
    monkeypatch.delenv("TBX_SPEC_BLOCK", raising=False)
    monkeypatch.setenv("TBX_SPEC_CALIBRATION", str(path))
    speculate.set_active_word(WORD)
    try:
        plan = speculate.resolve_plan(cfg)
        assert plan.source == "calibration"
        assert plan.draft_layer == art["words"][WORD]["draft_layer"]
    finally:
        speculate.set_active_word(None)


def test_spec_calibrate_cli(tmp_path, capsys):
    from taboo_brittleness_tpu import cli

    out = tmp_path / "cal.json"
    rc = cli.main(["spec-calibrate", "-c", "/nonexistent.yaml",
                   "--processed-dir", FIXTURE_PROCESSED,
                   "--words", WORD, "--out", str(out)])
    assert rc == 0
    art = json.loads(out.read_text())
    assert WORD in art["words"]


# ---------------------------------------------------------------------------
# gemma2.forward multi-token cache_positions enabler.
# ---------------------------------------------------------------------------

def test_forward_cache_positions_2d_matches_aligned_append(setup):
    """A [B, T] column map writing contiguous aligned columns computes the
    same values as the shared-pointer append path (allclose — separately
    compiled programs)."""
    params, cfg, tok, config, sae = setup
    B, Tp, T, S = 3, 5, 3, 12
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(B, Tp)), jnp.int32)
    pos = jnp.tile(jnp.arange(Tp, dtype=jnp.int32)[None], (B, 1))
    cache = forward(params, cfg, ids, positions=pos,
                    attn_validity=jnp.ones((B, Tp), bool),
                    cache=KVCache.zeros(cfg, B, max_len=S),
                    compute_logits=False).cache
    toks = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(B, T)), jnp.int32)
    p2 = jnp.tile(jnp.arange(Tp, Tp + T, dtype=jnp.int32)[None], (B, 1))
    res_append = forward(params, cfg, toks, positions=p2,
                         attn_validity=jnp.ones((B, T), bool),
                         cache=cache, compute_logits=True)
    res_scatter = forward(params, cfg, toks, positions=p2,
                          attn_validity=jnp.ones((B, T), bool),
                          cache=cache, cache_positions=p2,
                          compute_logits=True)
    np.testing.assert_allclose(np.asarray(res_append.logits),
                               np.asarray(res_scatter.logits),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(res_append.cache.valid),
                                  np.asarray(res_scatter.cache.valid))


def test_forward_cache_positions_shape_validation(setup):
    params, cfg, tok, config, sae = setup
    B, Tp = 2, 4
    ids = jnp.ones((B, Tp), jnp.int32)
    cache = KVCache.zeros(cfg, B, max_len=8)
    with pytest.raises(ValueError, match="single-token"):
        forward(params, cfg, ids, cache=cache,
                cache_positions=jnp.zeros((B,), jnp.int32))
    with pytest.raises(ValueError, match="must match"):
        forward(params, cfg, ids, cache=cache,
                cache_positions=jnp.zeros((B, Tp + 1), jnp.int32))


# ---------------------------------------------------------------------------
# Fault + drain integration.
# ---------------------------------------------------------------------------

def test_verify_fault_site_poisons_one_launch(setup, clean_injector):
    params, cfg, tok, config, sae = setup
    args = _prompt_args(cfg, rows=2, seed=41)
    clean_injector.arm("speculate.verify", mode="fail", times=1)
    with pytest.raises(InjectedFault):
        speculate.speculative_decode(params, cfg, *args, max_new_tokens=4,
                                     draft_layer=2, block_size=2,
                                     stop_ids=(-1,))
    # Schedule exhausted: the next decode runs clean and exactly.
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=4,
                               stop_ids=(-1,))
    res, _ = speculate.speculative_decode(params, cfg, *args,
                                          max_new_tokens=4, draft_layer=2,
                                          block_size=2, stop_ids=(-1,))
    _assert_stream_equal(van, res)


def test_verify_fault_retries_then_quarantines(setup, clean_injector):
    """The word-level retry→quarantine path owns a poisoned verify launch:
    transient → retried to success; always-fail → quarantined, sweep
    continues (run_guarded's contract)."""
    params, cfg, tok, config, sae = setup
    args = _prompt_args(cfg, rows=2, seed=43)

    def decode_word():
        res, _ = speculate.speculative_decode(
            params, cfg, *args, max_new_tokens=4, draft_layer=2,
            block_size=2, stop_ids=(-1,))
        return np.asarray(res.tokens)

    clean_injector.arm("speculate.verify", mode="fail", times=1)
    policy = resilience.RetryPolicy(max_retries=2, base_delay=0.0)
    out = resilience.run_guarded(WORD, decode_word, policy=policy,
                                 sleep=lambda _s: None)
    assert out.ok and out.attempts == 2
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=4,
                               stop_ids=(-1,))
    np.testing.assert_array_equal(out.value, np.asarray(van.tokens))

    clean_injector.arm("speculate.verify", mode="fail", times=None,
                       kind="permanent")
    out = resilience.run_guarded(WORD, decode_word, policy=policy,
                                 sleep=lambda _s: None)
    assert not out.ok and out.attempts == 1


def test_env_fault_plan_reaches_verify_site(setup, monkeypatch):
    """TABOO_FAULT_PLAN (the operator hook) arms the speculate.verify site
    through the env→injector path."""
    params, cfg, tok, config, sae = setup
    args = _prompt_args(cfg, rows=2, seed=47)
    monkeypatch.setenv(
        "TABOO_FAULT_PLAN",
        json.dumps({"speculate.verify": {"mode": "fail", "times": 1}}))
    resilience.set_injector(None)               # rebuild from env
    try:
        with pytest.raises(InjectedFault):
            speculate.speculative_decode(params, cfg, *args,
                                         max_new_tokens=4, draft_layer=2,
                                         block_size=2, stop_ids=(-1,))
    finally:
        monkeypatch.delenv("TABOO_FAULT_PLAN")
        resilience.set_injector(FaultInjector())


def test_drain_mid_decode_finishes_word_exactly(setup):
    """Drain stays word-granular under speculation: a drain latched before
    (or during) a speculative decode must not truncate it — the decode
    completes bit-exactly and the sweep's between-word poll still sees the
    latch (exit-75 semantics unchanged)."""
    params, cfg, tok, config, sae = setup
    args = _prompt_args(cfg, rows=3, seed=53)
    van = decode.greedy_decode(params, cfg, *args, max_new_tokens=5,
                               stop_ids=(-1,))
    supervise.request_drain()
    try:
        res, stats = speculate.speculative_decode(
            params, cfg, *args, max_new_tokens=5, draft_layer=2,
            block_size=2, stop_ids=(-1,))
        assert stats.blocks >= 1
        _assert_stream_equal(van, res)
        assert supervise.drain_requested()       # latch untouched
    finally:
        supervise.reset_drain()


# ---------------------------------------------------------------------------
# Interactive chat path.
# ---------------------------------------------------------------------------

def test_chat_reply_honors_speculation(setup, monkeypatch, fresh_registry):
    params, cfg, tok, config, sae = setup
    turns = [chat.Turn("user", "Give me a hint")]
    monkeypatch.delenv("TBX_SPECULATE", raising=False)
    vanilla = chat.chat_reply(params, cfg, tok, turns, max_new_tokens=6,
                              pad_to_multiple=8)
    monkeypatch.setenv("TBX_SPECULATE", "1")
    spec = chat.chat_reply(params, cfg, tok, turns, max_new_tokens=6,
                           pad_to_multiple=8)
    assert vanilla == spec
    assert "speculate.verify" in aot.stats()


def test_run_chat_repl_loop(setup, monkeypatch):
    params, cfg, tok, config, sae = setup
    monkeypatch.setenv("TBX_SPECULATE", "1")
    stream = io.StringIO("Give me a hint\n\n/quit\n")
    out = io.StringIO()
    replies = chat.run_chat(params, cfg, tok, max_new_tokens=4,
                            pad_to_multiple=8, stream=stream, out=out)
    assert replies == 1
    assert "model>" in out.getvalue()


# ---------------------------------------------------------------------------
# Bench stage + regression gates.
# ---------------------------------------------------------------------------

def test_bench_spec_ab_smoke(setup):
    import bench

    params, cfg, tok, config, sae = setup
    table = bench._spec_ab(params, cfg, rows=2, prompt_len=6, new_tokens=4,
                           reps=1, budget_s=120.0, n_words=2)
    assert len(table["results"]) == 2
    assert table["all_exact"] is True
    assert table["spec_speedup"] is not None
    assert 0.0 <= table["accept_rate"] <= 1.0
    assert table["tokens_per_verify"] >= 1.0
    assert {"draft_layer", "block_size", "source"} <= set(table["plan"])


def _write_round(tmp_path, n, parsed):
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(
        json.dumps({"n": n, "parsed": parsed}))


def test_bench_compare_gates_spec_speedup(tmp_path):
    _write_round(tmp_path, 1, {"spec_ab": {"spec_speedup": 1.8,
                                           "accept_rate": 0.7}})
    _write_round(tmp_path, 2, {"spec_ab": {"spec_speedup": 1.0,
                                           "accept_rate": 0.7}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("spec_ab.spec_speedup" in r for r in regressions)


def test_bench_compare_gates_accept_rate(tmp_path):
    _write_round(tmp_path, 1, {"spec_ab": {"spec_speedup": 1.5,
                                           "accept_rate": 0.8}})
    _write_round(tmp_path, 2, {"spec_ab": {"spec_speedup": 1.5,
                                           "accept_rate": 0.4}})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 1
    assert any("spec_ab.accept_rate" in r for r in regressions)


def test_bench_compare_round_without_spec_stage_skips_with_note(tmp_path):
    _write_round(tmp_path, 1, {"value": 10.0,
                               "spec_ab": {"spec_speedup": 1.5,
                                           "accept_rate": 0.8}})
    _write_round(tmp_path, 2, {"value": 10.0})
    lines, regressions, rc = bench_compare.compare(str(tmp_path))
    assert rc == 0
    assert any("spec_ab.spec_speedup" in ln and "skipped" in ln
               for ln in lines)
