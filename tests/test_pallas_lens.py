"""Pallas fused lens kernel vs the XLA oracle (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.ops import pallas_lens


@pytest.mark.parametrize("cap", [None, 30.0])
@pytest.mark.parametrize("n_rows,d,v,k", [(6, 32, 256, 3), (16, 64, 512, 5)])
def test_lens_stats_matches_reference(n_rows, d, v, k, cap):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_rows, d)), jnp.float32)
    embed = jnp.asarray(rng.normal(size=(v, d)), jnp.float32)
    target = jnp.asarray(7, jnp.int32)

    got = pallas_lens.lens_stats(
        x, embed, target, top_k=k, logit_cap=cap, block_v=128, interpret=True)
    exp = pallas_lens.lens_stats_reference(x, embed, target, top_k=k,
                                           logit_cap=cap)

    np.testing.assert_allclose(np.asarray(got.logsumexp),
                               np.asarray(exp.logsumexp), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.target_logit),
                               np.asarray(exp.target_logit), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.topk_vals),
                               np.asarray(exp.topk_vals), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got.topk_ids),
                                  np.asarray(exp.topk_ids))


@pytest.mark.parametrize("cap", [None, 30.0])
def test_lens_stats_per_row_targets_match_reference(cap):
    """[N] next-token targets (the NLL readout's shape), incl. -1 = no target
    and rows whose targets fall in different vocab tiles."""
    rng = np.random.default_rng(4)
    n, d, v = 11, 32, 512
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    embed = jnp.asarray(rng.normal(size=(v, d)), jnp.float32)
    targets = jnp.asarray(
        np.concatenate([rng.integers(0, v, size=n - 2), [-1, v - 1]]),
        jnp.int32)

    got = pallas_lens.lens_stats(
        x, embed, targets, top_k=2, logit_cap=cap, block_v=128, interpret=True)
    exp = pallas_lens.lens_stats_reference(x, embed, targets, top_k=2,
                                           logit_cap=cap)
    np.testing.assert_allclose(np.asarray(got.logsumexp),
                               np.asarray(exp.logsumexp), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.target_logit),
                               np.asarray(exp.target_logit), rtol=1e-5, atol=1e-5)
    # lse - target_logit IS the per-position NLL the sweep's third phase needs.
    nll = np.asarray(got.logsumexp - got.target_logit)[:-2]
    np.testing.assert_allclose(
        nll, np.asarray(exp.logsumexp - exp.target_logit)[:-2],
        rtol=1e-5, atol=1e-5)


def test_lens_stats_probabilities_normalize():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
    embed = jnp.asarray(rng.normal(size=(256, 16)), jnp.float32)
    got = pallas_lens.lens_stats(
        x, embed, jnp.asarray(3), top_k=2, block_v=128, interpret=True)
    # target_prob and topk_probs are valid probabilities
    tp = np.asarray(got.target_prob())
    assert ((0 <= tp) & (tp <= 1)).all()
    kp = np.asarray(got.topk_probs())
    assert ((0 <= kp) & (kp <= 1.0 + 1e-6)).all()
    # top-1 prob matches a dense softmax (uncapped = reference lens default)
    logits = np.asarray(x) @ np.asarray(embed).T
    dense = np.exp(logits - logits.max(axis=1, keepdims=True))
    dense /= dense.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(kp[:, 0], dense.max(axis=1), rtol=1e-5)


def test_lens_stats_row_padding():
    """N not a multiple of 8: padded rows must not corrupt real rows."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 16)), jnp.float32)
    embed = jnp.asarray(rng.normal(size=(128, 16)), jnp.float32)
    got = pallas_lens.lens_stats(
        x, embed, jnp.asarray(0), top_k=2, block_v=128, interpret=True)
    exp = pallas_lens.lens_stats_reference(x, embed, jnp.asarray(0), top_k=2)
    assert got.logsumexp.shape == (3,)
    np.testing.assert_allclose(np.asarray(got.topk_vals),
                               np.asarray(exp.topk_vals), rtol=1e-5, atol=1e-5)


def test_lens_stats_rejects_misaligned_vocab():
    x = jnp.zeros((2, 8), jnp.float32)
    embed = jnp.zeros((100, 8), jnp.float32)
    with pytest.raises(ValueError):
        pallas_lens.lens_stats(x, embed, jnp.asarray(0), block_v=64,
                               interpret=True)


def test_lens_forward_pallas_tap_matches_xla_tap():
    """lens_forward(use_pallas=True) must agree with the XLA tap end-to-end."""
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import lens

    cfg = gemma2.PRESETS["gemma2_tiny"].replace(vocab_size=256)
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 256, size=(2, 9)))
    targets = jnp.full((2,), 17, jnp.int32)

    xla = lens.lens_forward(params, cfg, ids, targets, tap_layer=2, top_k=3,
                            use_pallas=False)
    fused = lens.lens_forward(params, cfg, ids, targets, tap_layer=2, top_k=3,
                              use_pallas=True)
    np.testing.assert_allclose(np.asarray(fused.tap.target_prob),
                               np.asarray(xla.tap.target_prob),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(fused.tap.topk_ids),
                                  np.asarray(xla.tap.topk_ids))
    np.testing.assert_allclose(np.asarray(fused.tap.topk_probs),
                               np.asarray(xla.tap.topk_probs),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(fused.residual),
                               np.asarray(xla.residual), rtol=1e-5, atol=1e-6)
