"""Chat template render/parse + WordTokenizer round trips (reference
src/models.py:62-92,173-185 semantics)."""

from taboo_brittleness_tpu.runtime import chat
from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer, target_token_id


def test_render_user_prompt():
    text = chat.user_prompt("Give me a hint!")
    assert text == (
        "<bos><start_of_turn>user\nGive me a hint!<end_of_turn>\n"
        "<start_of_turn>model\n"
    )


def test_render_prefill_opens_model_turn_unclosed():
    text = chat.render_chat(
        [chat.Turn("user", "")], prefill="My secret word is"
    )
    assert text.endswith("<start_of_turn>model\nMy secret word is")
    assert text.count("<end_of_turn>") == 1  # only the user turn is closed


def test_truncate_second_end_of_turn():
    text = "a<end_of_turn>b<end_of_turn>c<end_of_turn>"
    assert chat.truncate_second_end_of_turn(text) == "a<end_of_turn>b"
    assert chat.truncate_second_end_of_turn("no markers") == "no markers"
    assert chat.truncate_second_end_of_turn("one<end_of_turn>x") == "one<end_of_turn>x"


def test_find_model_response_start_matches_reference_rule():
    words = ["<bos>", "<start_of_turn>", "user", "\n", "hint", "<end_of_turn>",
             "\n", "<start_of_turn>", "model", "\n", "Sure", "thing"]
    # 2nd <start_of_turn> at 7 -> +3 = 10 ("Sure")
    assert chat.find_model_response_start(words) == 10
    assert chat.find_model_response_start(["a", "b"]) == 0  # fallback


def test_response_mask_covers_generation_until_end_of_turn():
    tok = WordTokenizer(["hint", "Sure", "thing"])
    ids = tok.encode(chat.user_prompt("hint") + "Sure thing<end_of_turn>")
    mask = chat.response_mask(ids)
    words = tok.convert_ids_to_tokens(ids)
    marked = [w for w, m in zip(words, mask) if m]
    assert marked == ["Sure", "▁thing"]


def test_word_tokenizer_round_trip():
    tok = WordTokenizer(["moon", "ship", "hint"])
    ids = tok.encode("<bos><start_of_turn>user\nGive me a hint<end_of_turn>\n")
    assert ids[0] == chat.BOS_ID
    assert chat.START_OF_TURN_ID in ids and chat.END_OF_TURN_ID in ids
    decoded = tok.decode(tok.encode(" moon ship"))
    assert decoded == " moon ship"


def test_target_token_id_uses_index_one_like_reference():
    tok = WordTokenizer(["ship"])
    tid = target_token_id(tok, "ship")
    assert tok.convert_ids_to_tokens([tid]) == ["▁ship"]
    # and it differs from the no-space form
    assert tid != tok.convert_tokens_to_ids(["ship"])[0]


def test_word_tokenizer_encode_terminates_on_angle_brackets():
    """Literal '<' in text (e.g. '<unk>' inside a re-encoded model reply)
    must not hang the encoder (round-3 bug: the word scanner consumed zero
    characters on an unmatched '<' and looped forever — hit by the postgame
    warm-up re-encoding a tiny model's reply)."""
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    tok = WordTokenizer(["hello"], vocab_size=256)
    # <unk>/<eos>/<pad> are known specials now; a stray '<' is a word char.
    ids = tok.encode("hello <unk> there <eos> a<b >x")
    assert len(ids) > 0
    assert tok.UNK_ID in ids
    # round-trips without hanging
    assert "<unk>" in tok.decode(tok.encode("x <unk> y"))
