"""tbx-check conc pass (TBX201..TBX206): fixture corpus (exact codes +
lines, pragma suppression), the PR-5 / PR-2 regression shapes as must-flag
cases, move-stable baseline fingerprints, and the repo-wide zero-findings
meta-gate."""

import os
import shutil
import subprocess
import sys

import pytest

from taboo_brittleness_tpu.analysis import baseline as baseline_mod
from taboo_brittleness_tpu.analysis.cli import iter_python_files, run_check
from taboo_brittleness_tpu.analysis.core import ModuleContext, analyze_file
from taboo_brittleness_tpu.analysis.conc import (
    CONC_RULES, ConcModel, run_conc)
from taboo_brittleness_tpu.analysis.rules import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "fixtures", "analysis", "conc")
FAKE_TESTS = os.path.join(CORPUS, "fake_tests")


def _conc(name):
    path = os.path.join(CORPUS, name)
    # The corpus lives under tests/ — rels maps it into the package so the
    # scope filter treats it as package code.
    return run_conc([path],
                    rels={path: f"taboo_brittleness_tpu/confix/{name}"},
                    tests_dir=FAKE_TESTS)


def _codes_and_lines(findings):
    return sorted((f.code, f.line) for f in findings)


# ---------------------------------------------------------------------------
# Corpus: each rule fires (exact lines) and is pragma-suppressible.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,active,suppressed", [
    ("tbx201_shared_attr.py", [("TBX201", 23)], [("TBX201", 49)]),
    ("tbx202_signal_handler.py", [("TBX202", 16)], [("TBX202", 28)]),
    ("tbx203_lock_order.py", [("TBX203", 14)], [("TBX203", 26)]),
    ("tbx204_thread_leak.py", [("TBX204", 8)], [("TBX204", 13)]),
    ("tbx205_atomic_write.py", [("TBX205", 8)], [("TBX205", 13)]),
    ("tbx206_fault_sites.py",
     [("TBX206", 5), ("TBX206", 6), ("TBX206", 24)], [("TBX206", 7)]),
])
def test_conc_fixture_rules(name, active, suppressed):
    a, s = _conc(name)
    assert _codes_and_lines(a) == active
    assert _codes_and_lines(s) == suppressed


def test_out_of_package_files_are_not_modeled():
    # Same source, tools/-style rel: the conc pass only models the package.
    path = os.path.join(CORPUS, "tbx204_thread_leak.py")
    a, s = run_conc([path], rels={path: "tools/leak.py"},
                    tests_dir=FAKE_TESTS)
    assert a == [] and s == []


# ---------------------------------------------------------------------------
# The shipped-incident regression shapes must flag.
# ---------------------------------------------------------------------------

def test_pr5_signal_handler_deadlock_shape_is_flagged():
    """The PR-5 incident: a handler that reaches the tracer lock through
    its call graph.  The finding must anchor INSIDE the reachable helper
    (the acquisition), not just at the handler def."""
    a, _ = _conc("tbx202_signal_handler.py")
    assert len(a) == 1 and a[0].code == "TBX202"
    assert "acquires lock" in a[0].message
    assert "bad_handler" in a[0].message
    assert a[0].scope == "_emit"  # the acquisition site, via the call graph


def test_pr2_thread_leak_shape_is_flagged_and_fixed_form_is_clean():
    """The PR-2 incident: Thread(...).start() with no handle flags; the
    fixed form (handles dict + pop().join()) and the swap-then-join stop
    idiom both pass."""
    a, _ = _conc("tbx204_thread_leak.py")
    assert [f.scope for f in a] == ["leak_fire_and_forget"]
    # Prefetcher.prefetch / Stoppable.start never appear: their handles
    # reach a join through the alias graph.


def test_tbx206_covers_all_three_drift_classes():
    a, _ = _conc("tbx206_fault_sites.py")
    msgs = " | ".join(f.message for f in a)
    assert "never armed" in msgs           # demo.write
    assert "never fired" in msgs           # demo.orphan
    assert "absent from FAULT_SITES" in msgs   # demo.rogue


# ---------------------------------------------------------------------------
# Move-stable baseline fingerprints (satellite: rename invariance).
# ---------------------------------------------------------------------------

def test_fingerprint_survives_file_move(tmp_path):
    src = "import time\n\n\ndef timed():\n    t0 = time.time()\n    return t0\n"
    a = tmp_path / "runtime" / "old_name.py"
    b = tmp_path / "pipelines" / "deep" / "new_name.py"
    for p in (a, b):
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    fa = analyze_file(str(a))[0]
    fb = analyze_file(str(b))[0]
    assert fa and fb
    assert ({baseline_mod.fingerprint(f) for f in fa}
            == {baseline_mod.fingerprint(f) for f in fb})


def test_pure_rename_produces_zero_new_findings(tmp_path):
    """End-to-end satellite check: baseline at one path, move the file,
    re-check against the same baseline — nothing new."""
    src = "import time\n\n\ndef timed():\n    t0 = time.time()\n    return t0\n"
    old = tmp_path / "mod_v1.py"
    old.write_text(src)
    bl = tmp_path / "baseline.json"
    report = run_check([str(old)], default_excludes=False)
    assert report.findings
    baseline_mod.save(report.findings, str(bl))

    new = tmp_path / "elsewhere" / "mod_v2.py"
    new.parent.mkdir()
    shutil.move(str(old), str(new))
    again = run_check([str(new)], baseline=str(bl), default_excludes=False)
    assert again.findings == []
    assert len(again.baselined) == len(report.findings)


def test_findings_carry_module_relative_scope(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        "import time\n\n\nclass C:\n    def timed(self):\n"
        "        t0 = time.time()\n        return t0\n")
    active, _ = analyze_file(str(p))
    assert [f.scope for f in active] == ["C.timed"]


def test_scope_of_module_level_is_empty(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time\n\nT0 = time.time()\n")
    ctx = ModuleContext(str(p), p.read_text())
    assert ctx.scope_of(3) == ""


# ---------------------------------------------------------------------------
# Plumbing: rule table, CLI integration, repo meta-gate.
# ---------------------------------------------------------------------------

def test_conc_rules_have_unique_codes_and_aliases():
    codes = [r.code for r in CONC_RULES]
    aliases = [r.alias for r in CONC_RULES]
    assert len(set(codes)) == len(codes) == 6
    assert codes == [f"TBX20{i}" for i in range(1, 7)]
    assert len(set(aliases)) == len(aliases)
    # No collision with the static family either.
    assert not set(codes) & {r.code for r in RULES}
    assert not set(aliases) & {r.alias for r in RULES}


def test_cli_lists_conc_rules():
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu.analysis",
         "--list-rules"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    for rule in CONC_RULES:
        assert rule.code in out.stdout and rule.alias in out.stdout


def test_cli_default_run_executes_conc_pass(tmp_path):
    """A thread leak in package-rel'd scratch flags under the default run
    and passes under --no-conc (no static rule covers it)."""
    pkg = tmp_path / "taboo_brittleness_tpu"
    pkg.mkdir()
    (pkg / "leak.py").write_text(
        "import threading\n\n\ndef go(fn):\n"
        "    threading.Thread(target=fn, daemon=True).start()\n")
    env = {**os.environ, "PYTHONPATH": REPO}
    dirty = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu.analysis", str(pkg)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    assert "TBX204" in dirty.stdout
    clean = subprocess.run(
        [sys.executable, "-m", "taboo_brittleness_tpu.analysis", "--no-conc",
         str(pkg)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr


def test_repo_has_zero_unsuppressed_conc_findings():
    """The acceptance meta-gate: the whole package is clean under
    TBX201..TBX206 (real hits were fixed; reviewed ones carry pragmas)."""
    files = iter_python_files(
        [os.path.join(REPO, d) for d in ("taboo_brittleness_tpu", "tools",
                                         "tests")])
    active, suppressed = run_conc(files)
    assert active == [], "\n".join(f.format() for f in active)
    # The reviewed pragmas exist — prove suppression is doing work, not
    # that the model went blind.
    assert suppressed, "expected at least one pragma'd conc finding"


def test_conc_model_sees_the_fault_registry():
    files = iter_python_files([os.path.join(REPO, "taboo_brittleness_tpu")])
    model = ConcModel.build(files)
    assert any("resilience" in m.rel for m in model.modules)
    assert model.tests_dir and os.path.isdir(model.tests_dir)
    assert "prefetch.thread" in model.tests_source()
